"""On-chip component breakdown of the serving hot path.

Round-5 on-chip profiling found steady-state fused decode waves costing
500-770ms (expected ~105ms = tunnel RTT + HBM-bound compute) and packed
prefill ~330ms (expected ~80ms).  This script times each suspect in
isolation at the bench geometry (Llama-3.2-1B shape, batch 32, 16 fused
steps) so the next TPU window attributes the latency instead of
guessing.  Run by bench_daemon.py after the Mosaic gates; prints one
JSON line per component.

Components:
  roofline     chained 2048x8192 matmuls (MXU sanity, TFLOP/s)
  decode_full  the engine's real fused 16-step decode+sample dispatch
  model_only   16-step scan of model.decode without the sampler
  attn_pallas  16x16 paged decode attention calls (pallas) alone
  attn_xla     same with the XLA gather fallback
  sampler      16 chained sample() steps on [B, V] logits
  sampler_greedy  same logits, all-greedy batch (argmax path)
  kv_write     16x16 write_kv scatters
  prefill_packed  one packed 2x128-token prefill dispatch

Each timing first runs once to compile, then reports the median of 5
timed runs (block_until_ready between runs; timings include one tunnel
RTT each — subtract the reported `rtt_ms`).
"""

from __future__ import annotations

import functools
import json
import statistics
import time


def _med_ms(fn, n: int = 5) -> float:
    fn()  # compile / warm
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    return round(statistics.median(ts), 1)


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    import sys

    import jax.numpy as jnp
    import numpy as np

    import os

    allow_cpu = os.environ.get("PROFILE_ALLOW_CPU") == "1"
    if not allow_cpu:
        assert jax.default_backend() == "tpu", jax.default_backend()
    tiny = os.environ.get("PROFILE_TINY") == "1"
    sys.path.insert(0, ".")

    def emit(component: str, ms: float, extra: dict | None = None) -> None:
        line = {"component": component, "ms": ms, **(extra or {})}
        print(json.dumps(line), flush=True)

    # tunnel RTT reference: block on a trivial ready result
    x0 = jnp.ones((8, 128), jnp.bfloat16)
    probe_fn = jax.jit(lambda a: a * 2)
    rtt = _med_ms(lambda: probe_fn(x0).block_until_ready())
    emit("rtt", rtt)

    # ---- roofline
    w = jnp.ones((2048, 8192), jnp.bfloat16)
    h = jnp.ones((32, 2048), jnp.bfloat16)

    @jax.jit
    def chain(h, w):
        for _ in range(32):
            h = jnp.tanh(h @ w @ w.T * 1e-3)
        return h

    ms = _med_ms(lambda: chain(h, w).block_until_ready())
    tf = 32 * 2 * 2 * 32 * 2048 * 8192 / (ms / 1e3) / 1e12
    emit("roofline", ms, {"tflops": round(tf, 1)})

    # ---- engine pieces at bench geometry
    from bench import build_model_dir
    from transformers import AutoTokenizer

    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams
    from vllm_tgis_adapter_tpu.models.llama import LlamaForCausalLM

    model_dir, arch = build_model_dir(tiny)
    dtype = jnp.float32 if tiny else jnp.bfloat16
    prompt_len, max_seqs = (32, 4) if tiny else (128, 32)
    max_len = prompt_len + 144
    mcfg = ModelConfig(model=model_dir, model_type="llama",
                       max_model_len=max_len, rope_theta=500000.0,
                       dtype=dtype, **arch)
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16,
                                 num_blocks=max_seqs * 17 * 2,
                                 cache_dtype=dtype),
        scheduler_config=SchedulerConfig(
            max_num_seqs=max_seqs,
            prefill_buckets=(prompt_len, max_len),
            num_decode_steps=16),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )
    model = LlamaForCausalLM(mcfg)
    params = model.init_params(jax.random.PRNGKey(0))
    tok = AutoTokenizer.from_pretrained(model_dir)
    engine = LLMEngine(config, model, params, tok)
    rng = np.random.default_rng(0)
    for i in range(max_seqs):
        ids = rng.integers(3, mcfg.vocab_size, size=prompt_len).tolist()
        engine.add_request(
            f"r{i}", None,
            SamplingParams(temperature=0.0, max_tokens=64,
                           ignore_eos=True),
            prompt_token_ids=ids)

    # drive prefills through, timing one packed dispatch; stop at the
    # first decode plan and keep it for the wave timings below
    prefill_ms = None
    while True:
        outs, plan, prepared = engine.plan_step()
        if plan is None:
            break
        if type(plan).__name__ == "DecodePlan":
            break
        t0 = time.perf_counter()
        handle = engine.dispatch_step(plan, prepared)
        result = engine.wait_step(plan, prepared, handle)
        prefill_ms = round((time.perf_counter() - t0) * 1e3, 1)
        engine.commit_step(plan, result, prepared)
    emit("prefill_packed", prefill_ms or -1.0)
    assert plan is not None and type(plan).__name__ == "DecodePlan", plan
    runner = engine.runner

    def full_wave():
        handle = runner.dispatch_decode(prepared)
        runner.wait_decode(prepared, handle)

    emit("decode_full", _med_ms(full_wave),
         {"steps": prepared.num_steps,
          "batch": int(prepared.block_tables.shape[0])})

    # ---- model-only scan (no sampler): greedy argmax feedback
    b = prepared.block_tables.shape[0]
    ints, floats = runner._pack_decode_inputs(prepared)
    ints_d = jnp.asarray(ints)
    bt = jnp.asarray(prepared.block_tables)
    block_size = 16

    @functools.partial(jax.jit, static_argnums=(3,))
    def model_scan(params, caches, ints, num_steps):
        tokens0, positions0, limits = ints[0], ints[1], ints[2]
        context0, row_slots = ints[3], ints[4]
        max_blocks = bt.shape[1]

        def step(carry, k):
            caches, tokens = carry
            pos = positions0 + k
            active = (pos <= limits) & (row_slots >= 0)
            blk = jnp.take_along_axis(
                bt, jnp.clip(pos // block_size, 0, max_blocks - 1)[:, None],
                axis=1)[:, 0]
            slot = jnp.where(active, blk * block_size + pos % block_size, -1)
            logits, caches = model.decode(
                params, caches, tokens, pos, slot, bt,
                context0 + k, block_size, None, None)
            return (caches, jnp.argmax(logits, -1).astype(jnp.int32)), ()

        (caches, tokens), _ = jax.lax.scan(
            step, (caches, ints[0]), jnp.arange(num_steps))
        return tokens

    emit("model_only", _med_ms(
        lambda: model_scan(params, runner.caches, ints_d,
                           16).block_until_ready()))

    # ---- attention alone (pallas vs xla), 16 layers x 16 steps worth
    from vllm_tgis_adapter_tpu.ops import attention as attn_ops

    kc = runner.caches[0][0]
    vc = runner.caches[1][0]
    q = jnp.ones((b, arch["num_heads"], arch["head_dim"]), dtype)
    cl = jnp.asarray(prepared.context_lens
                     if hasattr(prepared, "context_lens")
                     else np.full(b, 140, np.int32))

    n_calls = 4 if tiny else 16 * 16  # layers x fused steps

    def attn_loop(impl):
        @jax.jit
        def many(q, kc, vc, bt, cl):
            acc = q
            for _ in range(n_calls):
                acc = impl(acc, kc, vc, bt, cl)
            return acc

        return _med_ms(lambda: many(q, kc, vc, bt, cl).block_until_ready())

    from vllm_tgis_adapter_tpu.ops import ragged_attention as ragged_ops

    def ragged_decode(q, kc, vc, bt, cl):
        # one-token spans: the serving decode path (the bucketed
        # folded/perhead variant ladder is retired)
        n = q.shape[0]
        return ragged_ops.ragged_paged_attention(
            q, kc, vc, jnp.maximum(cl, 1) - 1,
            jnp.arange(n + 1, dtype=jnp.int32),
            jnp.maximum(cl, 1) - 1, jnp.asarray(n, jnp.int32),
            bt, 16, 0.125,
        )

    emit(f"attn_ragged_{n_calls}calls", attn_loop(ragged_decode))
    emit(f"attn_xla_{n_calls}calls", attn_loop(
        lambda q, kc, vc, bt, cl: attn_ops.paged_decode_attention_xla(
            q, kc, vc, bt, cl, 16, 0.125)))

    # ---- sampler alone: 16 chained steps, sampled vs greedy
    from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod

    logits = jnp.ones((b, mcfg.vocab_size), jnp.float32)
    seen = runner.seen

    def build_tensors(greedy: bool):
        t = sampler_mod.SamplingTensors(
            temperature=jnp.full(b, 0.0 if greedy else 0.9, jnp.float32),
            top_k=jnp.full(b, 0 if greedy else 40, jnp.int32),
            top_p=jnp.full(b, 1.0 if greedy else 0.9, jnp.float32),
            typical_p=jnp.ones(b, jnp.float32),
            repetition_penalty=jnp.full(b, 1.0 if greedy else 1.1,
                                        jnp.float32),
            len_penalty_start=jnp.full(b, 10 ** 9, jnp.int32),
            len_penalty_decay=jnp.ones(b, jnp.float32),
            min_tokens=jnp.zeros(b, jnp.int32),
            eos_token_id=jnp.full(b, -1, jnp.int32),
            gen_len=jnp.zeros(b, jnp.int32),
            base_key=jnp.arange(b, dtype=jnp.uint32),
        )

        @jax.jit
        def sample16(logits, seen, t):
            def step(carry, k):
                logits, seen = carry
                out = sampler_mod.sample(
                    logits, jnp.take(seen, jnp.arange(b), axis=0), t)
                logits = logits + out.tokens[:, None] * 1e-6
                return (logits, seen), out.tokens

            (_, _), toks = jax.lax.scan(step, (logits, seen),
                                        jnp.arange(16))
            return toks

        return lambda: sample16(logits, seen, t).block_until_ready()

    emit("sampler_sampled_16", _med_ms(build_tensors(False)))
    emit("sampler_greedy_16", _med_ms(build_tensors(True)))

    # ---- kv write scatter alone
    kx = jnp.ones((b, arch["num_kv_heads"], arch["head_dim"]), dtype)
    slots = jnp.arange(b, dtype=jnp.int32) * 16

    @jax.jit
    def scatter_many(kc, vc, kx, slots):
        for _ in range(n_calls):
            kc, vc = attn_ops.write_kv(kc, vc, kx, kx, slots)
        return kc[0, 0, 0]

    emit(f"kv_write_{n_calls}", _med_ms(
        lambda: scatter_many(kc, vc, kx, slots).block_until_ready()))


if __name__ == "__main__":
    main()
