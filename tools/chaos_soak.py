"""Randomized chaos soak: property-based recovery coverage (nox -s chaos_soak).

``chaos_check`` proves hand-picked recovery scenarios; this harness
proves the *surface*: a SEEDED schedule draws faults (``raise`` /
``oom`` / ``hang``) across the failpoint sites while a mixed
chat/RAG/LoRA workload runs against a supervised engine with the host
KV tier on (some seeds dp=2; some of THOSE run a disaggregated
prefill+decode fleet and always arm the kill-prefill-replica-
mid-handoff fault — docs/SCALING.md "Disaggregated roles"; a fixed
rotation of seeds serves with --kv-quantization int8/fp8, proving
checkpoint/resume and cross-replica migration token-stable under
QUANTIZED KV pages — docs/QUANTIZATION.md).  The closed-loop engine
this harness drives (fixtures, engine build, request driving, seeded
workloads) lives in tools/scenarios.py — the steady-state suites and
this soak share one workload engine.  Asserted here are the global
invariants no single scenario can
(docs/RECOVERY.md "Randomized chaos soak"):

* every submitted request reaches EXACTLY ONE terminal outcome — a
  completed stream or a typed retryable ``EngineRestartError`` — and
  nothing outlives the harness bound (no watchdog-visible hangs);
* every request that completes streams TOKEN-IDENTICAL output to its
  uncrashed baseline (greedy and seeded-sampled alike, resumed from a
  decode checkpoint or not), with zero duplicate/missing DELTA tokens;
* the engine returns to ``serving`` after every injected fault within
  the bound;
* checkpoint/resume adds ZERO new compile shapes over the warmed set
  for its entry points (``gather_kv`` / ``scatter_kv`` ride one fixed
  block shape each — compile-tracker gated).

Each seed is one reproducible schedule: ``python tools/chaos_soak.py
--seed 7`` replays exactly what CI saw.  ``--recovery-bench`` runs the
perf gate instead (tools/perf_check.py ``recovery`` section): one long
request killed mid-decode must complete, resumed, within
``max_ratio`` x its uncrashed wall time — with the JAX persistent
compilation cache on, so the rebuilt engine's recompiles cost what a
TPU restart with a warm XLA cache pays, not a cold build.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# every soak step runs the invariant sanitizer (engine/sanitizer.py):
# a fault schedule that corrupts allocator/arena/tier/pool accounting
# fails AT the corrupting step, not as a downstream token divergence
os.environ.setdefault("TGIS_TPU_SANITIZE", "1")

from tools.scenarios import (  # noqa: E402 — after sys.path insert
    build_engine,
    build_fixtures,
    make_mixed_workload,
    run_request,
)

DEFAULT_SEEDS = 5
DEFAULT_BASE_SEED = 20260804
#: nothing — request, recovery, or drain — may outlive this (the soak's
#: watchdog bound; the in-engine stall watchdog runs far tighter)
HARNESS_BOUND_S = 60.0
#: soft overall budget: exceeded → loud warning, never silent trimming
BUDGET_S = 120.0

REQUESTS_PER_SEED = 8

#: deterministic --kv-quantization rotation per seed (seed % 3): does
#: not perturb the rng draw sequence of pre-existing schedules, and
#: the default 5-seed CI run always covers int8 AND fp8 — every fault,
#: checkpoint/resume and cross-replica migration in those schedules
#: then runs over quantized pages + scale sidecars, with the
#: token-identity invariant held against the SAME-engine baseline
KV_QUANT_ROTATION = ("none", "int8", "fp8")

# (site, action) pool the schedule draws from.  ``hang`` is listed once
# and only used at dp=1 seeds (the watchdog declares the stall and the
# supervisor restarts the replica — detection needs the stalled replica
# to be the one with work, which dp=2 placement makes nondeterministic).
FAULTS = (
    ("core.plan_step", "raise"),
    ("core.commit_step", "raise"),
    ("core.wait_step", "oom"),
    ("scheduler.schedule", "raise"),
    ("runner.dispatch_ragged", "raise"),
    ("runner.dispatch_decode", "raise"),
    # mid-spec-verify death (docs/ATTENTION.md "Speculative decoding"):
    # fires inside the verify dispatch, AFTER the draft proposed but
    # BEFORE any acceptance committed — the checkpoint/resume path must
    # capture only ACCEPTED tokens (in-flight draft tokens die with the
    # dispatch) and resume token-identically.  On non-spec seeds the
    # schedule remaps this to the plain ragged dispatch site.
    ("runner.dispatch_verify", "raise"),
    ("core.wait_step", "hang"),
    # armed in one round, fires during a LATER round's recovery: the
    # death-during-recovery retry, which must adopt the failed
    # attempt's staged checkpoints instead of losing them
    ("supervisor.rebuild", "raise"),
)


# fixture build, engine construction, seeded workloads and request
# driving were PROMOTED into tools/scenarios.py (the steady-state suite
# engine); the soak keeps only the chaos schedule and its invariants
_build_fixtures = build_fixtures
_run_request = run_request


def _build_engine(model_dir: str, *, dp: int, watchdog: bool,
                  roles: tuple = (), spec: bool = False,
                  kv_quantization: str = "none"):
    return build_engine(
        model_dir, dp=dp, watchdog=watchdog, roles=roles, spec=spec,
        kv_quantization=kv_quantization,
    )


def _make_workload(rng: random.Random) -> list[dict]:
    return make_mixed_workload(rng, REQUESTS_PER_SEED)


async def _wait_serving(engine, what: str, bound: float) -> None:
    deadline = time.monotonic() + bound
    while time.monotonic() < deadline:
        if engine.lifecycle == "serving" and all(
            rep.serving for rep in engine._replicas  # noqa: SLF001
        ):
            return
        await asyncio.sleep(0.02)
    raise AssertionError(
        f"seed invariant violated: {what} did not return to serving "
        f"within {bound:.0f}s (lifecycle={engine.lifecycle})"
    )


async def _run_seed(seed: int, model_dir: str, adapter_dir: str) -> dict:
    from vllm_tgis_adapter_tpu import compile_tracker
    from vllm_tgis_adapter_tpu.frontdoor.errors import EngineRestartError
    from vllm_tgis_adapter_tpu.supervisor import failpoints

    rng = random.Random(seed)
    dp = 2 if rng.random() < 0.4 else 1
    # disaggregated-roles seeds: a dp=2 fleet split prefill+decode.
    # Every request then crosses the handoff boundary, and the seed
    # ALWAYS arms the kill-prefill-replica-mid-handoff fault below —
    # role-aware recovery (staged handoffs resume on the decode
    # sibling) is asserted by the same token-identity invariants.
    roles = (
        ("prefill", "decode")
        if dp == 2 and rng.random() < 0.7
        else ()
    )
    # speculative seeds: ~60% of schedules attach the same-weights
    # draft (greedy requests then ride verify spans; seeded-sampled
    # ones stay on plain spans in the SAME dispatches) — composed with
    # dp, roles and every fault in the pool
    spec_on = rng.random() < 0.6
    # quantized-KV seeds: a fixed seed-keyed rotation (not an rng draw,
    # so existing schedules keep their exact fault sequence) serves
    # some schedules with int8/fp8 KV pages — checkpoints, resumes,
    # cross-replica migration and role handoffs then move quantized
    # pages + scale sidecars, and the token-identity invariant (vs the
    # same engine's uncrashed baseline) proves the page scale
    # discipline reproducible across every recompute path
    kvq = KV_QUANT_ROTATION[seed % len(KV_QUANT_ROTATION)]
    engine = _build_engine(
        model_dir, dp=dp, watchdog=(dp == 1), roles=roles, spec=spec_on,
        kv_quantization=kvq,
    )
    hang_released: list[str] = []
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        specs = _make_workload(rng)

        # ---- warm phase: the uncrashed BASELINE, and the compile set.
        # Running the identical workload first (a) pins the per-request
        # correct outputs and (b) compiles every shape the chaos phase
        # can reach; the re-send of spec 0 exercises one host-tier
        # promotion so scatter_kv is in the warmed set too.
        baseline: dict[int, list[int]] = {}
        for i, spec in enumerate(specs):
            status, toks = await _run_request(
                engine, f"warm-{seed}-{i}", spec, lora_req
            )
            assert status == "ok", f"warm request {i} failed: {toks!r}"
            baseline[i] = toks
        status, toks = await _run_request(
            engine, f"warm-{seed}-promote", specs[0], lora_req
        )
        assert status == "ok" and toks == baseline[0], (
            "warm re-send diverged — prefix/tier reuse broke determinism"
        )
        warm_shapes = compile_tracker.shapes()

        # ---- chaos phase: same workload, seeded fault schedule
        injected: list[str] = []
        if roles:
            # kill-prefill-replica-mid-handoff: armed BEFORE the
            # workload, so the first handoff drain dies BETWEEN stage
            # and resume — the staged records survive in the
            # fleet-shared tier and role-aware recovery must adopt
            # them onto the decode sibling (docs/SCALING.md)
            failpoints.arm_site("async.handoff", "raise", 1)
            injected.append("async.handoff=raise")
        tasks = {
            i: asyncio.create_task(_run_request(
                engine, f"chaos-{seed}-{i}", spec, lora_req
            ))
            for i, spec in enumerate(specs)
        }
        for _ in range(rng.randint(1, 3)):
            await asyncio.sleep(rng.uniform(0.1, 0.6))
            if all(t.done() for t in tasks.values()):
                break
            site, action = rng.choice(FAULTS)
            if action == "hang" and dp != 1:
                site, action = "core.plan_step", "raise"
            if site == "runner.dispatch_verify" and not spec_on:
                # no draft attached: the verify site never fires —
                # remap to the plain ragged dispatch so the draw still
                # injects a fault
                site = "runner.dispatch_ragged"
            injected.append(f"{site}={action}")
            failpoints.arm_site(site, action, 1)
            if action == "hang":
                # the stall watchdog declares it and the supervisor
                # restarts the replica; the abandoned worker thread is
                # released once recovery is observed
                await _wait_serving(
                    engine, f"hang recovery ({site})", HARNESS_BOUND_S
                )
                failpoints.release(site)
                hang_released.append(site)
            else:
                await _wait_serving(
                    engine, f"recovery after {site}={action}",
                    HARNESS_BOUND_S,
                )

        done, pending = await asyncio.wait(
            tasks.values(), timeout=HARNESS_BOUND_S
        )
        assert not pending, (
            "seed invariant violated: "
            f"{len(pending)} request(s) hung past the "
            f"{HARNESS_BOUND_S:.0f}s harness bound"
        )
        await _wait_serving(engine, "post-chaos engine", HARNESS_BOUND_S)

        ok = retryable = 0
        for i, task in tasks.items():
            status, payload = task.result()
            if status == "ok":
                if payload != baseline[i] and os.environ.get("CHAOS_DEBUG"):
                    rid = f"chaos-{seed}-{i}"
                    for rep_i, e in enumerate(
                        rep.engine for rep in engine._replicas
                    ):
                        for ev in e.recorder.events_for(rid):
                            print("DBG", rep_i, ev)
                assert payload == baseline[i], (
                    f"seed invariant violated: request {i} "
                    f"({specs[i]['kind']}) completed but its streamed "
                    f"tokens diverged from the uncrashed baseline\n"
                    f"  baseline: {baseline[i]}\n  got:      {payload}"
                )
                ok += 1
            else:
                assert isinstance(payload, EngineRestartError), (
                    "seed invariant violated: request "
                    f"{i} terminated with an untyped error: {payload!r}"
                )
                retryable += 1

        # compile discipline: checkpoint/resume rides the fixed-shape
        # per-page programs — across ANY number of checkpoints, pages
        # and resumes, gather/scatter each hold exactly one compiled
        # shape (their first compile may land lazily at the first
        # checkpoint; what must never happen is a SECOND shape), and
        # no other entry point gains a shape the warm phase lacked
        for fn in ("gather_kv", "scatter_kv"):
            fn_shapes = {
                s for s in compile_tracker.shapes() if s[0] == fn
            }
            assert len(fn_shapes) <= 1, (
                "seed invariant violated: checkpoint/resume entry "
                f"point {fn} compiled {len(fn_shapes)} shapes: "
                f"{sorted(fn_shapes)}"
            )
        new_shapes = {
            s for s in compile_tracker.shapes() - warm_shapes
            if s[0] not in ("gather_kv", "scatter_kv")
            and s[0].startswith(("gather", "scatter"))
        }
        assert not new_shapes, (
            "seed invariant violated: unexpected checkpoint/resume "
            f"shapes: {sorted(new_shapes)}"
        )

        restarts = len([
            h for h in (engine.supervisor.restart_history or [])
            if h.get("recovered")
        ])
        resumed = sum(
            h.get("resumed", 0)
            for h in engine.supervisor.restart_history
        )
        if roles:
            # role-aware recovery invariants: the fleet actually handed
            # work off (the warm phase alone guarantees >= 1), the
            # armed mid-handoff kill recovered the PREFILL replica with
            # its role intact, and at least one staged handoff was
            # adopted and resumed rather than lost
            assert engine.handoff_outcomes["completed"] >= 1, (
                "roles seed invariant violated: no handoff completed"
            )
            assert any(
                h.get("recovered") and h.get("replica") == 0
                for h in engine.supervisor.restart_history
            ), (
                "roles seed invariant violated: the prefill replica "
                "was not killed+recovered by the armed handoff fault"
            )
            assert engine._replicas[0].role == "prefill"  # noqa: SLF001
            assert resumed >= 1, (
                "roles seed invariant violated: the mid-handoff kill's "
                "staged records were not adopted and resumed"
            )
        return {
            "seed": seed,
            "dp": dp,
            "roles": list(roles) or None,
            "kv_quantization": kvq,
            "requests": len(specs),
            "ok": ok,
            "retryable": retryable,
            "faults": injected,
            "restarts": restarts,
            "resumed": resumed,
            **({"handoffs": dict(engine.handoff_outcomes)}
               if roles else {}),
        }
    finally:
        # a count=1 fault that never fired must not bleed into the next
        # seed's engine; disarm also frees any still-parked hang thread
        failpoints.disarm()
        try:
            await engine.stop()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _cross_host_soak(model_dir: str) -> dict:
    """``--cross-host``: the networked-KV-tier fault family over a real
    loopback TCP fleet (docs/CROSS_HOST.md).

    Two engines in one process — ``A`` prefill-only, ``B`` mixed —
    peered over localhost sockets, exactly the two-process topology's
    wire traffic.  Asserted, against a kvnet-less baseline engine:

    * corrupt-payload: a flipped byte in a remote page blob is a MISS
      (checksum), the span recomputes locally, tokens identical;
    * slow-peer / partition: a peer slower than the timeout (and a
      ``kvnet.get`` failpoint) degrade to the local tiers — the request
      completes token-identically or fails TYPED-retryable, and both
      engines keep serving (a dead remote never stalls the step loop);
    * remote handoff: with the fleet healthy, a request on the
      prefill-only host decodes on the peer, token-identical;
    * machine loss: ``A`` dies mid-decode of a handed-off request —
      ``B`` adopts it, and the union of the tokens streamed before the
      kill with ``B``'s banked tail equals the baseline exactly (zero
      lost outputs).
    """
    from vllm_tgis_adapter_tpu.frontdoor.errors import EngineRestartError
    from vllm_tgis_adapter_tpu.supervisor import failpoints

    prompt = [5 + (i % 40) for i in range(48)]  # 3 full pages at bs=16
    spec = {"kind": "chat", "prompt": prompt, "temperature": 0.0,
            "seed": None, "max_tokens": 12, "logprobs": None}
    long_spec = {**spec, "max_tokens": 48}

    def _fleet_engine(**kw):  # noqa: ANN003, ANN202
        return build_engine(
            model_dir, kv_host_cache_gb=1.0,
            # prefix registration demotes prompt pages at prefill
            # commit, making them INDEX-visible without LRU pressure
            enable_prefix_caching=False,
            **kw,
        )

    # ---- uncrashed kvnet-less baseline
    base_engine = _fleet_engine()
    await base_engine.start()
    status, base = await _run_request(base_engine, "xh-base", spec, None)
    assert status == "ok", f"baseline failed: {base!r}"
    status, base_long = await _run_request(
        base_engine, "xh-base-long", long_spec, None
    )
    assert status == "ok", f"long baseline failed: {base_long!r}"
    await base_engine.stop()

    port_a, port_b = _free_port(), _free_port()
    a = _fleet_engine(
        roles=("prefill",),
        kvnet_listen=f"127.0.0.1:{port_a}",
        kvnet_peers=(f"127.0.0.1:{port_b}",), kvnet_node_id="A",
        kvnet_timeout_s=1.0,
    )
    b = _fleet_engine(
        kvnet_listen=f"127.0.0.1:{port_b}",
        kvnet_peers=(f"127.0.0.1:{port_a}",), kvnet_node_id="B",
        kvnet_timeout_s=1.0,
    )
    stats: dict = {}
    consumer = None
    try:
        await a.start()
        await b.start()

        # warm the fleet-shared prefix on B; wait for A's mirror of it
        status, toks = await _run_request(b, "xh-warm", spec, None)
        assert status == "ok" and toks == base, "warm on B diverged"
        for _ in range(200):
            if a.kvnet.peers[0].mirror:
                break
            await asyncio.sleep(0.05)
        assert a.kvnet.peers[0].mirror, (
            "cross-host invariant violated: A never mirrored B's INDEX"
        )

        # ---- fault family: each fault, one request on A (remote
        # prefix fetch from B + remote handoff back to B)
        outcomes: dict[str, str] = {}
        peer = a.kvnet.peers[0]
        for fault in ("corrupt", "slow_peer", "partition", "healthy"):
            if fault == "corrupt":
                peer.corrupt_next = True
            elif fault == "slow_peer":
                peer.delay_s = 2.5  # > kvnet_timeout_s: every RPC times out
            elif fault == "partition":
                failpoints.arm_site("kvnet.get", "raise", 1)
            t0 = time.monotonic()
            status, payload = await asyncio.wait_for(
                _run_request(a, f"xh-{fault}", spec, None),
                timeout=HARNESS_BOUND_S,
            )
            elapsed = time.monotonic() - t0
            if status == "ok":
                assert payload == base, (
                    f"cross-host invariant violated: {fault} request "
                    f"completed but diverged from baseline\n"
                    f"  baseline: {base}\n  got:      {payload}"
                )
                outcomes[fault] = "ok"
            else:
                # a prefill-only host with its one peer unreachable has
                # no decode path — typed-retryable is the ladder floor
                assert fault in ("slow_peer", "partition"), (
                    f"cross-host invariant violated: {fault} request "
                    f"failed ({payload!r}) instead of degrading to the "
                    "local tiers"
                )
                assert isinstance(payload, EngineRestartError), (
                    "cross-host invariant violated: untyped error "
                    f"under {fault}: {payload!r}"
                )
                outcomes[fault] = "retryable"
            assert elapsed < HARNESS_BOUND_S, "fault stalled the loop"
            # corrupt/healthy MUST complete: the remote rung degrades
            # per-page, never per-request
            if fault in ("corrupt", "healthy"):
                assert outcomes[fault] == "ok", (
                    f"{fault} request did not complete"
                )
            peer.delay_s = 0.0
            peer.corrupt_next = False
            failpoints.disarm()
            if fault in ("slow_peer", "partition"):
                # wait for the heartbeat to revive the peer before the
                # next leg (down peers are skipped, not retried inline)
                for _ in range(200):
                    if peer.state == "healthy":
                        break
                    await asyncio.sleep(0.05)
        assert a.kvnet.remote._hits > 0, (  # noqa: SLF001
            "cross-host invariant violated: no remote prefix page was "
            "ever served (the healthy leg should have hit B's mirror)"
        )

        # ---- machine loss: kill A mid-decode of a handed-off request
        got: list[int] = []

        async def _consume() -> None:
            # stream INCREMENTALLY (a real client banks every DELTA as
            # it arrives): tokens A emitted before dying must count —
            # run_request's end-of-stream return would discard them
            from tools.scenarios import _params

            try:
                async for out in a.generate(
                    prompt=None,
                    sampling_params=_params(long_spec),
                    request_id="xh-lost",
                    prompt_token_ids=list(long_spec["prompt"]),
                ):
                    got.extend(out.outputs[0].token_ids)
            except asyncio.CancelledError:
                pass
            except Exception:  # noqa: BLE001 — A dying mid-stream is the point
                pass

        # hold B's replica lock so the cross-host resume blocks right
        # after queue registration — the kill below lands before any
        # decode step, deterministically
        async with b._replicas[0].lock:  # noqa: SLF001
            consumer = asyncio.ensure_future(_consume())
            for _ in range(5000):
                if "xh-lost" in b._queues:  # noqa: SLF001
                    break
                await asyncio.sleep(0.005)
            assert "xh-lost" in b._queues, (  # noqa: SLF001
                "handoff never registered on the survivor"
            )
            await a.kvnet.stop()  # the machine-loss event
            await asyncio.sleep(0.2)
        deadline = time.monotonic() + HARNESS_BOUND_S
        while time.monotonic() < deadline:
            if "xh-lost" in b.kvnet.completed:
                break
            await asyncio.sleep(0.1)
        tail: list[int] = []
        for out in b.kvnet.completed.get("xh-lost", []):
            tail.extend(out.outputs[0].token_ids)
        assert got + tail == base_long, (
            "cross-host invariant violated: streamed+banked tokens "
            "after machine loss diverged from baseline\n"
            f"  baseline ({len(base_long)}): {base_long}\n"
            f"  streamed ({len(got)}) + banked ({len(tail)}): "
            f"{got + tail}"
        )
        stats = {
            "mode": "cross_host",
            "fault_outcomes": outcomes,
            "remote_hits": a.kvnet.remote._hits,  # noqa: SLF001
            "loss_streamed": len(got),
            "loss_banked": len(tail),
            "baseline_tokens": len(base_long),
        }
        return stats
    finally:
        failpoints.disarm()
        if consumer is not None:
            consumer.cancel()
            await asyncio.gather(consumer, return_exceptions=True)
        for eng in (a, b):
            try:
                await eng.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass


async def _recovery_bench(model_dir: str) -> dict:
    """perf_check ``recovery`` gate: one long greedy request killed
    mid-decode must complete RESUMED within ``max_ratio`` x its
    uncrashed wall time.

    Measurement discipline (CPU-proxy fidelity): on the tiny fixture,
    decode is ~0.3 ms/token while re-TRACING the fused decode programs
    on ANY fresh engine costs seconds — a warm-baseline ratio would
    measure JAX tracing, not recovery.  Both sides therefore run with
    COLD per-engine programs over one shared persistent XLA cache:
    the baseline is the request's wall time on a freshly built engine,
    the resumed side is the same request crashed mid-decode (its
    rebuilt engine is equally cold).  The ratio then isolates exactly
    what checkpoint/resume adds: the quiesce gathers, the rebuild, the
    tier promotion, and the tail recompute."""
    from vllm_tgis_adapter_tpu.supervisor import failpoints

    spec = {
        "kind": "chat",
        "prompt": list(range(3, 21)),
        "max_tokens": 384,
        "temperature": 0.0,
        "seed": None,
    }

    # populate the shared persistent XLA cache (and the decode-tail
    # step variants a resume can land on) so neither measured side
    # pays a first-ever backend compile
    warm = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        for k in range(8):
            status, _ = await _run_request(
                warm, f"tailwarm-{k}", {**spec, "max_tokens": 9 + k},
                None,
            )
            assert status == "ok"
        status, base_toks = await _run_request(warm, "full", spec, None)
        assert status == "ok"
    finally:
        await warm.stop()

    # baseline: cold-program engine, uncrashed
    base = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        t0 = time.perf_counter()
        status, got = await asyncio.wait_for(
            _run_request(base, "base", spec, None), HARNESS_BOUND_S
        )
        base_s = time.perf_counter() - t0
        assert status == "ok" and got == base_toks
    finally:
        await base.stop()

    # resumed: cold-program engine, killed mid-decode; the rebuilt
    # engine is cold the same way the baseline engine was
    engine = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        t0 = time.perf_counter()
        task = asyncio.create_task(
            _run_request(engine, "crashed", spec, None)
        )
        deadline = time.monotonic() + HARNESS_BOUND_S
        while time.monotonic() < deadline:
            seq = engine.engine._seqs.get("crashed")  # noqa: SLF001
            # >= 1 COMMITTED (already-streamed) token = mid-decode; the
            # soak kills at arbitrary depths — here the kill lands at
            # the first token so the ratio measures recovery, not how
            # many decode programs happened to trace twice
            if seq is not None and seq.num_output_tokens >= 1:
                break
            await asyncio.sleep(0.005)
        failpoints.arm_site("core.plan_step", "raise", 1)
        status, resumed_toks = await asyncio.wait_for(
            task, HARNESS_BOUND_S
        )
        resumed_s = time.perf_counter() - t0
        assert status == "ok", f"resumed request failed: {resumed_toks!r}"
        history = engine.supervisor.restart_history
        return {
            "kind": "recovery",
            "base_s": round(base_s, 3),
            "resumed_s": round(resumed_s, 3),
            "ratio": round(resumed_s / max(base_s, 1e-9), 3),
            "token_identical": resumed_toks == base_toks,
            "resumed": sum(h.get("resumed", 0) for h in history),
        }
    finally:
        failpoints.disarm()
        try:
            await engine.stop()
        except Exception:  # noqa: BLE001
            pass


def _enable_persistent_compile_cache() -> None:
    """Warm-XLA-cache fidelity for the recovery bench: a rebuilt
    engine's recompiles should cost what a TPU restart with the
    persistent compilation cache pays, not a cold build."""
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        tempfile.mkdtemp(prefix="chaos-soak-xla-cache-"),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seeds", type=int, default=DEFAULT_SEEDS,
                        help="number of seeds (schedules) to run")
    parser.add_argument("--base-seed", type=int,
                        default=DEFAULT_BASE_SEED)
    parser.add_argument("--seed", type=int, default=None,
                        help="run exactly one seed (reproduce a CI run)")
    parser.add_argument("--recovery-bench", action="store_true",
                        help="run the perf_check recovery measurement "
                             "and print one JSON line")
    parser.add_argument("--cross-host", action="store_true",
                        help="run the networked-KV-tier fault family "
                             "(corrupt/slow-peer/partition + "
                             "kill-mid-decode machine loss) over a "
                             "loopback TCP fleet — docs/CROSS_HOST.md")
    args = parser.parse_args(argv)

    _enable_persistent_compile_cache()
    model_dir, adapter_dir = _build_fixtures()

    if args.recovery_bench:
        line = asyncio.run(_recovery_bench(model_dir))
        print(json.dumps(line))
        return 0

    if args.cross_host:
        try:
            stats = asyncio.run(_cross_host_soak(model_dir))
        except AssertionError as e:
            print(f"chaos_soak: cross-host FAILED: {e}")
            return 1
        print(
            "chaos_soak: cross-host green — faults "
            f"{stats['fault_outcomes']} "
            f"remote_hits={stats['remote_hits']} machine-loss "
            f"streamed+banked={stats['loss_streamed']}+"
            f"{stats['loss_banked']} == "
            f"baseline={stats['baseline_tokens']}"
        )
        return 0

    seeds = (
        [args.seed]
        if args.seed is not None
        else [args.base_seed + i for i in range(args.seeds)]
    )
    t0 = time.monotonic()
    failures = 0
    for seed in seeds:
        try:
            stats = asyncio.run(_run_seed(seed, model_dir, adapter_dir))
        except AssertionError as e:
            failures += 1
            print(f"chaos_soak: seed {seed} FAILED: {e}")
            continue
        roles_note = (
            f" roles={','.join(stats['roles'])} "
            f"handoffs={stats['handoffs']['completed']}"
            if stats.get("roles")
            else ""
        )
        print(
            f"chaos_soak: seed {stats['seed']} ok  dp={stats['dp']} "
            f"kvq={stats['kv_quantization']} "
            f"requests={stats['requests']} "
            f"(ok={stats['ok']} retryable={stats['retryable']}) "
            f"faults=[{', '.join(stats['faults'])}] "
            f"restarts={stats['restarts']} resumed={stats['resumed']}"
            f"{roles_note}"
        )
    elapsed = time.monotonic() - t0
    if elapsed > BUDGET_S:
        print(
            f"chaos_soak: WARNING — {elapsed:.0f}s exceeded the "
            f"{BUDGET_S:.0f}s budget (all {len(seeds)} seed(s) still "
            "ran; nothing was trimmed)"
        )
    if failures:
        print(
            f"chaos_soak: {failures}/{len(seeds)} seed(s) violated an "
            "invariant — reproduce with "
            "`python tools/chaos_soak.py --seed <n>`"
        )
        return 1
    print(
        f"chaos_soak: all {len(seeds)} seed(s) green in {elapsed:.0f}s "
        "(one terminal outcome per request, token-identical resumes, "
        "no harness-bound hangs, zero new checkpoint/resume shapes)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
