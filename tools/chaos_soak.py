"""Randomized chaos soak: property-based recovery coverage (nox -s chaos_soak).

``chaos_check`` proves hand-picked recovery scenarios; this harness
proves the *surface*: a SEEDED schedule draws faults (``raise`` /
``oom`` / ``hang``) across the failpoint sites while a mixed
chat/RAG/LoRA workload runs against a supervised engine with the host
KV tier on (some seeds dp=2; some of THOSE run a disaggregated
prefill+decode fleet and always arm the kill-prefill-replica-
mid-handoff fault — docs/SCALING.md "Disaggregated roles"; a fixed
rotation of seeds serves with --kv-quantization int8/fp8, proving
checkpoint/resume and cross-replica migration token-stable under
QUANTIZED KV pages — docs/QUANTIZATION.md).  The closed-loop engine
this harness drives (fixtures, engine build, request driving, seeded
workloads) lives in tools/scenarios.py — the steady-state suites and
this soak share one workload engine.  Asserted here are the global
invariants no single scenario can
(docs/RECOVERY.md "Randomized chaos soak"):

* every submitted request reaches EXACTLY ONE terminal outcome — a
  completed stream or a typed retryable ``EngineRestartError`` — and
  nothing outlives the harness bound (no watchdog-visible hangs);
* every request that completes streams TOKEN-IDENTICAL output to its
  uncrashed baseline (greedy and seeded-sampled alike, resumed from a
  decode checkpoint or not), with zero duplicate/missing DELTA tokens;
* the engine returns to ``serving`` after every injected fault within
  the bound;
* checkpoint/resume adds ZERO new compile shapes over the warmed set
  for its entry points (``gather_kv`` / ``scatter_kv`` ride one fixed
  block shape each — compile-tracker gated).

Each seed is one reproducible schedule: ``python tools/chaos_soak.py
--seed 7`` replays exactly what CI saw.  ``--recovery-bench`` runs the
perf gate instead (tools/perf_check.py ``recovery`` section): one long
request killed mid-decode must complete, resumed, within
``max_ratio`` x its uncrashed wall time — with the JAX persistent
compilation cache on, so the rebuilt engine's recompiles cost what a
TPU restart with a warm XLA cache pays, not a cold build.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# every soak step runs the invariant sanitizer (engine/sanitizer.py):
# a fault schedule that corrupts allocator/arena/tier/pool accounting
# fails AT the corrupting step, not as a downstream token divergence
os.environ.setdefault("TGIS_TPU_SANITIZE", "1")

from tools.scenarios import (  # noqa: E402 — after sys.path insert
    build_engine,
    build_fixtures,
    make_mixed_workload,
    run_request,
)

DEFAULT_SEEDS = 5
DEFAULT_BASE_SEED = 20260804
#: nothing — request, recovery, or drain — may outlive this (the soak's
#: watchdog bound; the in-engine stall watchdog runs far tighter)
HARNESS_BOUND_S = 60.0
#: soft overall budget: exceeded → loud warning, never silent trimming
BUDGET_S = 120.0

REQUESTS_PER_SEED = 8

#: deterministic --kv-quantization rotation per seed (seed % 3): does
#: not perturb the rng draw sequence of pre-existing schedules, and
#: the default 5-seed CI run always covers int8 AND fp8 — every fault,
#: checkpoint/resume and cross-replica migration in those schedules
#: then runs over quantized pages + scale sidecars, with the
#: token-identity invariant held against the SAME-engine baseline
KV_QUANT_ROTATION = ("none", "int8", "fp8")

# (site, action) pool the schedule draws from.  ``hang`` is listed once
# and only used at dp=1 seeds (the watchdog declares the stall and the
# supervisor restarts the replica — detection needs the stalled replica
# to be the one with work, which dp=2 placement makes nondeterministic).
FAULTS = (
    ("core.plan_step", "raise"),
    ("core.commit_step", "raise"),
    ("core.wait_step", "oom"),
    ("scheduler.schedule", "raise"),
    ("runner.dispatch_ragged", "raise"),
    ("runner.dispatch_decode", "raise"),
    # mid-spec-verify death (docs/ATTENTION.md "Speculative decoding"):
    # fires inside the verify dispatch, AFTER the draft proposed but
    # BEFORE any acceptance committed — the checkpoint/resume path must
    # capture only ACCEPTED tokens (in-flight draft tokens die with the
    # dispatch) and resume token-identically.  On non-spec seeds the
    # schedule remaps this to the plain ragged dispatch site.
    ("runner.dispatch_verify", "raise"),
    ("core.wait_step", "hang"),
    # armed in one round, fires during a LATER round's recovery: the
    # death-during-recovery retry, which must adopt the failed
    # attempt's staged checkpoints instead of losing them
    ("supervisor.rebuild", "raise"),
)


# fixture build, engine construction, seeded workloads and request
# driving were PROMOTED into tools/scenarios.py (the steady-state suite
# engine); the soak keeps only the chaos schedule and its invariants
_build_fixtures = build_fixtures
_run_request = run_request


def _build_engine(model_dir: str, *, dp: int, watchdog: bool,
                  roles: tuple = (), spec: bool = False,
                  kv_quantization: str = "none"):
    return build_engine(
        model_dir, dp=dp, watchdog=watchdog, roles=roles, spec=spec,
        kv_quantization=kv_quantization,
    )


def _make_workload(rng: random.Random) -> list[dict]:
    return make_mixed_workload(rng, REQUESTS_PER_SEED)


async def _wait_serving(engine, what: str, bound: float) -> None:
    deadline = time.monotonic() + bound
    while time.monotonic() < deadline:
        if engine.lifecycle == "serving" and all(
            rep.serving for rep in engine._replicas  # noqa: SLF001
        ):
            return
        await asyncio.sleep(0.02)
    raise AssertionError(
        f"seed invariant violated: {what} did not return to serving "
        f"within {bound:.0f}s (lifecycle={engine.lifecycle})"
    )


async def _run_seed(seed: int, model_dir: str, adapter_dir: str) -> dict:
    from vllm_tgis_adapter_tpu import compile_tracker
    from vllm_tgis_adapter_tpu.frontdoor.errors import EngineRestartError
    from vllm_tgis_adapter_tpu.supervisor import failpoints

    rng = random.Random(seed)
    dp = 2 if rng.random() < 0.4 else 1
    # disaggregated-roles seeds: a dp=2 fleet split prefill+decode.
    # Every request then crosses the handoff boundary, and the seed
    # ALWAYS arms the kill-prefill-replica-mid-handoff fault below —
    # role-aware recovery (staged handoffs resume on the decode
    # sibling) is asserted by the same token-identity invariants.
    roles = (
        ("prefill", "decode")
        if dp == 2 and rng.random() < 0.7
        else ()
    )
    # speculative seeds: ~60% of schedules attach the same-weights
    # draft (greedy requests then ride verify spans; seeded-sampled
    # ones stay on plain spans in the SAME dispatches) — composed with
    # dp, roles and every fault in the pool
    spec_on = rng.random() < 0.6
    # quantized-KV seeds: a fixed seed-keyed rotation (not an rng draw,
    # so existing schedules keep their exact fault sequence) serves
    # some schedules with int8/fp8 KV pages — checkpoints, resumes,
    # cross-replica migration and role handoffs then move quantized
    # pages + scale sidecars, and the token-identity invariant (vs the
    # same engine's uncrashed baseline) proves the page scale
    # discipline reproducible across every recompute path
    kvq = KV_QUANT_ROTATION[seed % len(KV_QUANT_ROTATION)]
    engine = _build_engine(
        model_dir, dp=dp, watchdog=(dp == 1), roles=roles, spec=spec_on,
        kv_quantization=kvq,
    )
    hang_released: list[str] = []
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        specs = _make_workload(rng)

        # ---- warm phase: the uncrashed BASELINE, and the compile set.
        # Running the identical workload first (a) pins the per-request
        # correct outputs and (b) compiles every shape the chaos phase
        # can reach; the re-send of spec 0 exercises one host-tier
        # promotion so scatter_kv is in the warmed set too.
        baseline: dict[int, list[int]] = {}
        for i, spec in enumerate(specs):
            status, toks = await _run_request(
                engine, f"warm-{seed}-{i}", spec, lora_req
            )
            assert status == "ok", f"warm request {i} failed: {toks!r}"
            baseline[i] = toks
        status, toks = await _run_request(
            engine, f"warm-{seed}-promote", specs[0], lora_req
        )
        assert status == "ok" and toks == baseline[0], (
            "warm re-send diverged — prefix/tier reuse broke determinism"
        )
        warm_shapes = compile_tracker.shapes()

        # ---- chaos phase: same workload, seeded fault schedule
        injected: list[str] = []
        if roles:
            # kill-prefill-replica-mid-handoff: armed BEFORE the
            # workload, so the first handoff drain dies BETWEEN stage
            # and resume — the staged records survive in the
            # fleet-shared tier and role-aware recovery must adopt
            # them onto the decode sibling (docs/SCALING.md)
            failpoints.arm_site("async.handoff", "raise", 1)
            injected.append("async.handoff=raise")
        tasks = {
            i: asyncio.create_task(_run_request(
                engine, f"chaos-{seed}-{i}", spec, lora_req
            ))
            for i, spec in enumerate(specs)
        }
        for _ in range(rng.randint(1, 3)):
            await asyncio.sleep(rng.uniform(0.1, 0.6))
            if all(t.done() for t in tasks.values()):
                break
            site, action = rng.choice(FAULTS)
            if action == "hang" and dp != 1:
                site, action = "core.plan_step", "raise"
            if site == "runner.dispatch_verify" and not spec_on:
                # no draft attached: the verify site never fires —
                # remap to the plain ragged dispatch so the draw still
                # injects a fault
                site = "runner.dispatch_ragged"
            injected.append(f"{site}={action}")
            failpoints.arm_site(site, action, 1)
            if action == "hang":
                # the stall watchdog declares it and the supervisor
                # restarts the replica; the abandoned worker thread is
                # released once recovery is observed
                await _wait_serving(
                    engine, f"hang recovery ({site})", HARNESS_BOUND_S
                )
                failpoints.release(site)
                hang_released.append(site)
            else:
                await _wait_serving(
                    engine, f"recovery after {site}={action}",
                    HARNESS_BOUND_S,
                )

        done, pending = await asyncio.wait(
            tasks.values(), timeout=HARNESS_BOUND_S
        )
        assert not pending, (
            "seed invariant violated: "
            f"{len(pending)} request(s) hung past the "
            f"{HARNESS_BOUND_S:.0f}s harness bound"
        )
        await _wait_serving(engine, "post-chaos engine", HARNESS_BOUND_S)

        ok = retryable = 0
        for i, task in tasks.items():
            status, payload = task.result()
            if status == "ok":
                if payload != baseline[i] and os.environ.get("CHAOS_DEBUG"):
                    rid = f"chaos-{seed}-{i}"
                    for rep_i, e in enumerate(
                        rep.engine for rep in engine._replicas
                    ):
                        for ev in e.recorder.events_for(rid):
                            print("DBG", rep_i, ev)
                assert payload == baseline[i], (
                    f"seed invariant violated: request {i} "
                    f"({specs[i]['kind']}) completed but its streamed "
                    f"tokens diverged from the uncrashed baseline\n"
                    f"  baseline: {baseline[i]}\n  got:      {payload}"
                )
                ok += 1
            else:
                assert isinstance(payload, EngineRestartError), (
                    "seed invariant violated: request "
                    f"{i} terminated with an untyped error: {payload!r}"
                )
                retryable += 1

        # compile discipline: checkpoint/resume rides the fixed-shape
        # per-page programs — across ANY number of checkpoints, pages
        # and resumes, gather/scatter each hold exactly one compiled
        # shape (their first compile may land lazily at the first
        # checkpoint; what must never happen is a SECOND shape), and
        # no other entry point gains a shape the warm phase lacked
        for fn in ("gather_kv", "scatter_kv"):
            fn_shapes = {
                s for s in compile_tracker.shapes() if s[0] == fn
            }
            assert len(fn_shapes) <= 1, (
                "seed invariant violated: checkpoint/resume entry "
                f"point {fn} compiled {len(fn_shapes)} shapes: "
                f"{sorted(fn_shapes)}"
            )
        new_shapes = {
            s for s in compile_tracker.shapes() - warm_shapes
            if s[0] not in ("gather_kv", "scatter_kv")
            and s[0].startswith(("gather", "scatter"))
        }
        assert not new_shapes, (
            "seed invariant violated: unexpected checkpoint/resume "
            f"shapes: {sorted(new_shapes)}"
        )

        restarts = len([
            h for h in (engine.supervisor.restart_history or [])
            if h.get("recovered")
        ])
        resumed = sum(
            h.get("resumed", 0)
            for h in engine.supervisor.restart_history
        )
        if roles:
            # role-aware recovery invariants: the fleet actually handed
            # work off (the warm phase alone guarantees >= 1), the
            # armed mid-handoff kill recovered the PREFILL replica with
            # its role intact, and at least one staged handoff was
            # adopted and resumed rather than lost
            assert engine.handoff_outcomes["completed"] >= 1, (
                "roles seed invariant violated: no handoff completed"
            )
            assert any(
                h.get("recovered") and h.get("replica") == 0
                for h in engine.supervisor.restart_history
            ), (
                "roles seed invariant violated: the prefill replica "
                "was not killed+recovered by the armed handoff fault"
            )
            assert engine._replicas[0].role == "prefill"  # noqa: SLF001
            assert resumed >= 1, (
                "roles seed invariant violated: the mid-handoff kill's "
                "staged records were not adopted and resumed"
            )
        return {
            "seed": seed,
            "dp": dp,
            "roles": list(roles) or None,
            "kv_quantization": kvq,
            "requests": len(specs),
            "ok": ok,
            "retryable": retryable,
            "faults": injected,
            "restarts": restarts,
            "resumed": resumed,
            **({"handoffs": dict(engine.handoff_outcomes)}
               if roles else {}),
        }
    finally:
        # a count=1 fault that never fired must not bleed into the next
        # seed's engine; disarm also frees any still-parked hang thread
        failpoints.disarm()
        try:
            await engine.stop()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass


async def _recovery_bench(model_dir: str) -> dict:
    """perf_check ``recovery`` gate: one long greedy request killed
    mid-decode must complete RESUMED within ``max_ratio`` x its
    uncrashed wall time.

    Measurement discipline (CPU-proxy fidelity): on the tiny fixture,
    decode is ~0.3 ms/token while re-TRACING the fused decode programs
    on ANY fresh engine costs seconds — a warm-baseline ratio would
    measure JAX tracing, not recovery.  Both sides therefore run with
    COLD per-engine programs over one shared persistent XLA cache:
    the baseline is the request's wall time on a freshly built engine,
    the resumed side is the same request crashed mid-decode (its
    rebuilt engine is equally cold).  The ratio then isolates exactly
    what checkpoint/resume adds: the quiesce gathers, the rebuild, the
    tier promotion, and the tail recompute."""
    from vllm_tgis_adapter_tpu.supervisor import failpoints

    spec = {
        "kind": "chat",
        "prompt": list(range(3, 21)),
        "max_tokens": 384,
        "temperature": 0.0,
        "seed": None,
    }

    # populate the shared persistent XLA cache (and the decode-tail
    # step variants a resume can land on) so neither measured side
    # pays a first-ever backend compile
    warm = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        for k in range(8):
            status, _ = await _run_request(
                warm, f"tailwarm-{k}", {**spec, "max_tokens": 9 + k},
                None,
            )
            assert status == "ok"
        status, base_toks = await _run_request(warm, "full", spec, None)
        assert status == "ok"
    finally:
        await warm.stop()

    # baseline: cold-program engine, uncrashed
    base = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        t0 = time.perf_counter()
        status, got = await asyncio.wait_for(
            _run_request(base, "base", spec, None), HARNESS_BOUND_S
        )
        base_s = time.perf_counter() - t0
        assert status == "ok" and got == base_toks
    finally:
        await base.stop()

    # resumed: cold-program engine, killed mid-decode; the rebuilt
    # engine is cold the same way the baseline engine was
    engine = _build_engine(model_dir, dp=1, watchdog=False)
    try:
        t0 = time.perf_counter()
        task = asyncio.create_task(
            _run_request(engine, "crashed", spec, None)
        )
        deadline = time.monotonic() + HARNESS_BOUND_S
        while time.monotonic() < deadline:
            seq = engine.engine._seqs.get("crashed")  # noqa: SLF001
            # >= 1 COMMITTED (already-streamed) token = mid-decode; the
            # soak kills at arbitrary depths — here the kill lands at
            # the first token so the ratio measures recovery, not how
            # many decode programs happened to trace twice
            if seq is not None and seq.num_output_tokens >= 1:
                break
            await asyncio.sleep(0.005)
        failpoints.arm_site("core.plan_step", "raise", 1)
        status, resumed_toks = await asyncio.wait_for(
            task, HARNESS_BOUND_S
        )
        resumed_s = time.perf_counter() - t0
        assert status == "ok", f"resumed request failed: {resumed_toks!r}"
        history = engine.supervisor.restart_history
        return {
            "kind": "recovery",
            "base_s": round(base_s, 3),
            "resumed_s": round(resumed_s, 3),
            "ratio": round(resumed_s / max(base_s, 1e-9), 3),
            "token_identical": resumed_toks == base_toks,
            "resumed": sum(h.get("resumed", 0) for h in history),
        }
    finally:
        failpoints.disarm()
        try:
            await engine.stop()
        except Exception:  # noqa: BLE001
            pass


def _enable_persistent_compile_cache() -> None:
    """Warm-XLA-cache fidelity for the recovery bench: a rebuilt
    engine's recompiles should cost what a TPU restart with the
    persistent compilation cache pays, not a cold build."""
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        tempfile.mkdtemp(prefix="chaos-soak-xla-cache-"),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seeds", type=int, default=DEFAULT_SEEDS,
                        help="number of seeds (schedules) to run")
    parser.add_argument("--base-seed", type=int,
                        default=DEFAULT_BASE_SEED)
    parser.add_argument("--seed", type=int, default=None,
                        help="run exactly one seed (reproduce a CI run)")
    parser.add_argument("--recovery-bench", action="store_true",
                        help="run the perf_check recovery measurement "
                             "and print one JSON line")
    args = parser.parse_args(argv)

    _enable_persistent_compile_cache()
    model_dir, adapter_dir = _build_fixtures()

    if args.recovery_bench:
        line = asyncio.run(_recovery_bench(model_dir))
        print(json.dumps(line))
        return 0

    seeds = (
        [args.seed]
        if args.seed is not None
        else [args.base_seed + i for i in range(args.seeds)]
    )
    t0 = time.monotonic()
    failures = 0
    for seed in seeds:
        try:
            stats = asyncio.run(_run_seed(seed, model_dir, adapter_dir))
        except AssertionError as e:
            failures += 1
            print(f"chaos_soak: seed {seed} FAILED: {e}")
            continue
        roles_note = (
            f" roles={','.join(stats['roles'])} "
            f"handoffs={stats['handoffs']['completed']}"
            if stats.get("roles")
            else ""
        )
        print(
            f"chaos_soak: seed {stats['seed']} ok  dp={stats['dp']} "
            f"kvq={stats['kv_quantization']} "
            f"requests={stats['requests']} "
            f"(ok={stats['ok']} retryable={stats['retryable']}) "
            f"faults=[{', '.join(stats['faults'])}] "
            f"restarts={stats['restarts']} resumed={stats['resumed']}"
            f"{roles_note}"
        )
    elapsed = time.monotonic() - t0
    if elapsed > BUDGET_S:
        print(
            f"chaos_soak: WARNING — {elapsed:.0f}s exceeded the "
            f"{BUDGET_S:.0f}s budget (all {len(seeds)} seed(s) still "
            "ran; nothing was trimmed)"
        )
    if failures:
        print(
            f"chaos_soak: {failures}/{len(seeds)} seed(s) violated an "
            "invariant — reproduce with "
            "`python tools/chaos_soak.py --seed <n>`"
        )
        return 1
    print(
        f"chaos_soak: all {len(seeds)} seed(s) green in {elapsed:.0f}s "
        "(one terminal outcome per request, token-identical resumes, "
        "no harness-bound hangs, zero new checkpoint/resume shapes)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
