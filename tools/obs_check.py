"""Docs ↔ registry drift gate (nox -s obs_check).

Boots the real HTTP server in-process against a tiny fixture model,
scrapes ``/metrics`` over a real socket, and fails if any metric name
documented in docs/OBSERVABILITY.md is missing from the scrape (the
three flight-recorder/watchdog metrics included).  Also hits
``GET /debug/state`` and fails if the snapshot is missing any of the
top-level sections the doc promises — the introspection surface and its
documentation cannot drift silently either.  The step-anatomy/doctor
surfaces are gated the same way: ``?section=`` filtering,
``GET /debug/doctor``, and the ``GET /debug/timeline`` chrome trace are
exercised over the live server, and the doc's regime rule table must
match ``telemetry.doctor.REGIMES`` exactly.  Run directly with
``JAX_PLATFORMS=cpu python tools/obs_check.py``.
"""

from __future__ import annotations

import asyncio
import os
import re
import socket
import sys
import tempfile
import urllib.request
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def documented_event_kinds(doc_path: Path) -> set[str]:
    """Backticked kind names from the first column of the
    "Event schema" table — every flight-recorder kind the doc
    promises (combined rows like ``swap_out`` / ``swap_in`` yield both
    names)."""
    kinds: set[str] = set()
    in_table = False
    for line in doc_path.read_text().splitlines():
        if line.startswith("| Kind |"):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            first_cell = line.split("|")[1]
            kinds.update(re.findall(r"`([a-z_]+)`", first_cell))
    return kinds


def documented_metrics(doc_path: Path) -> set[str]:
    """Backticked ``tgis_tpu_*`` names from the observability doc
    (placeholder suffixes like ``pp{N}`` never name a whole metric)."""
    text = doc_path.read_text()
    return {
        name
        for name in re.findall(r"`(tgis_tpu_[a-z0-9_]+)`", text)
    }


# top-level sections docs/OBSERVABILITY.md documents for the
# /debug/state snapshot; a missing key means code and doc diverged
DEBUG_STATE_KEYS = (
    "engine", "supervisor", "frontdoor", "router", "kv_host_tier",
    "ledger",
    "slo",
    "step_timeline",
    "doctor",
    "replicas",
    "compile_tracker",
    "watchdog",
    "events",
)
REPLICA_KEYS = ("scheduler", "kv_cache", "in_flight", "step_counter",
                "serving", "role", "adapter_pool", "arena")
# kv_host_tier section: the per-rung split (ISSUE 14 satellite — the
# host and disk budgets must never read as one silently-summed number)
KV_TIER_KEYS = ("tiers",)
# "remote" is always a key (None when no kvnet manager is attached) so
# the networked rung can't silently drop out of the hierarchy snapshot
KV_TIER_TIERS = ("host", "disk", "remote")
# router-section keys the doc promises (incl. the disaggregation
# additions: per-role queue depths and handoff outcomes)
ROUTER_KEYS = ("placed_by_policy", "affinity_hit_rate",
               "role_queue_depths", "handoffs")

# the front-door metric surface (docs/FRONTDOOR.md) must BOTH be
# documented in docs/OBSERVABILITY.md and appear on /metrics — adding a
# frontdoor metric without documenting it fails here, not in review
REQUIRED_FRONTDOOR_METRICS = (
    "tgis_tpu_frontdoor_queue_depth",
    "tgis_tpu_frontdoor_queue_age_seconds",
    "tgis_tpu_frontdoor_sheds_total",
    "tgis_tpu_frontdoor_tenant_tokens_total",
    "tgis_tpu_frontdoor_placement_total",
)

# the telemetry signal layer (docs/OBSERVABILITY.md "Cost ledger" /
# "SLO burn rates"): the cost-attribution counters, the SLO gauges,
# and the live efficiency gauges must all BOTH be documented and
# served — the elastic control plane reads these, so silent drift here
# is an autoscaler flying blind
REQUIRED_TELEMETRY_METRICS = (
    "tgis_tpu_tenant_cost_tokens_total",
    "tgis_tpu_tenant_cost_hbm_page_seconds_total",
    "tgis_tpu_tenant_cost_tier_bytes_total",
    "tgis_tpu_slo_attainment",
    "tgis_tpu_slo_burn_rate",
    "tgis_tpu_spec_acceptance_rate_ewma",
    "tgis_tpu_model_tflops_per_s",
    "tgis_tpu_mfu",
)

# step anatomy + bottleneck doctor (docs/OBSERVABILITY.md "Step
# anatomy & doctor"): the phase histograms, the device-idle gauge, and
# the episode counters must be documented AND served
REQUIRED_STEPTIME_METRICS = (
    "tgis_tpu_step_anatomy_seconds",
    "tgis_tpu_host_gap_frac",
    "tgis_tpu_doctor_episodes_total",
    "tgis_tpu_doctor_active_regimes",
)

# networked KV tier (kvnet/, docs/CROSS_HOST.md): the cross-host
# sharing/handoff surface must be documented AND served — operators
# diagnose a partitioned or slow peer from exactly these names, so
# drift here means a fleet incident debugged blind
REQUIRED_KVNET_METRICS = (
    "tgis_tpu_kvnet_remote_lookups_total",
    "tgis_tpu_kvnet_remote_hits_total",
    "tgis_tpu_kvnet_remote_hit_ratio",
    "tgis_tpu_kvnet_transfer_bytes_total",
    "tgis_tpu_kvnet_peer_rtt_seconds",
    "tgis_tpu_kvnet_peers",
    "tgis_tpu_kvnet_handoffs_total",
)


def documented_regimes(doc_path: Path) -> set[str]:
    """Backticked regime names from the first column of the doctor's
    "Regime rule table" in docs/OBSERVABILITY.md — cross-checked
    against ``telemetry.doctor.REGIMES`` so the doc's rule table and
    the classifier cannot drift."""
    regimes: set[str] = set()
    in_table = False
    for line in doc_path.read_text().splitlines():
        if line.startswith("| Regime |"):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            first_cell = line.split("|")[1]
            regimes.update(re.findall(r"`([a-z_]+)`", first_cell))
    return regimes


async def scrape_metrics() -> tuple[str, dict]:
    from tests.fixture_models import build_tiny_llama

    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import EngineConfig
    from vllm_tgis_adapter_tpu.http import build_http_server, run_http_server
    from vllm_tgis_adapter_tpu.tgis_utils.args import (
        make_parser,
        postprocess_tgis_args,
    )

    model_dir = tempfile.mkdtemp(prefix="obs-check-model-")
    build_tiny_llama(model_dir)

    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]

    old_argv = sys.argv
    sys.argv = [
        "obs_check", "--model", model_dir, "--max-model-len", "512",
        "--dtype", "float32", "--max-num-seqs", "4",
        "--port", str(port),
    ]
    try:
        args = postprocess_tgis_args(make_parser().parse_args())
    finally:
        sys.argv = old_argv

    engine = AsyncLLMEngine.from_config(EngineConfig.from_args(args))
    await engine.start()
    app = build_http_server(args, engine)
    server_task = asyncio.create_task(
        run_http_server(args, engine, app, sock)
    )
    try:
        for _ in range(50):
            await asyncio.sleep(0.1)
            try:
                body = await asyncio.to_thread(
                    lambda: urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5
                    ).read()
                )
            except OSError:
                continue
            import json

            def fetch(path: str) -> bytes:
                return urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5
                ).read()

            state_body = await asyncio.to_thread(fetch, "/debug/state")
            # the ?section= filter, the doctor view, and the chrome
            # trace exercised over the SAME live server, so the new
            # debug surfaces are gated end-to-end, not just imported
            section_body = await asyncio.to_thread(
                fetch, "/debug/state?section=doctor,step_timeline"
            )
            doctor_body = await asyncio.to_thread(fetch, "/debug/doctor")
            timeline_body = await asyncio.to_thread(
                fetch, "/debug/timeline?format=chrome"
            )
            return (
                body.decode(),
                json.loads(state_body),
                json.loads(section_body),
                json.loads(doctor_body),
                json.loads(timeline_body),
            )
        raise RuntimeError("HTTP server never became scrapeable")
    finally:
        server_task.cancel()
        try:
            await server_task
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
        await engine.stop()


def check_event_kinds(doc_path: Path) -> list[str]:
    """Three-way flight-recorder kind agreement: the doc's event-schema
    table, ``flight_recorder.EVENT_KINDS``, and the lifecycle-grammar
    manifest (request ∪ batch kinds) must list the SAME set — adding a
    kind without documenting it AND declaring its grammar edges fails
    here, not in review."""
    from tools.dettest import lifecycle_grammar

    from vllm_tgis_adapter_tpu.flight_recorder import EVENT_KINDS

    code_kinds = set(EVENT_KINDS)
    problems: list[str] = []
    for label, other in (
        ("docs/OBSERVABILITY.md event-schema table",
         documented_event_kinds(doc_path)),
        ("lifecycle grammar manifest "
         "(tools/dettest/lifecycle_grammar.py request ∪ batch kinds)",
         set(lifecycle_grammar.all_kinds())),
    ):
        missing = sorted(code_kinds - other)
        extra = sorted(other - code_kinds)
        if missing:
            problems.append(
                f"{label} is missing kind(s): {', '.join(missing)}"
            )
        if extra:
            problems.append(
                f"{label} lists kind(s) absent from "
                f"flight_recorder.EVENT_KINDS: {', '.join(extra)}"
            )
    return problems


def main() -> int:
    doc_path = REPO_ROOT / "docs" / "OBSERVABILITY.md"
    kind_problems = check_event_kinds(doc_path)
    if kind_problems:
        print("obs_check: flight-recorder kind lists diverged:")
        for problem in kind_problems:
            print(f"  {problem}")
        return 1
    documented = documented_metrics(REPO_ROOT / "docs" / "OBSERVABILITY.md")
    if not documented:
        print("obs_check: no metrics documented — parse failure?")
        return 1
    undocumented = sorted(
        name
        for name in REQUIRED_FRONTDOOR_METRICS
        + REQUIRED_TELEMETRY_METRICS
        + REQUIRED_STEPTIME_METRICS
        + REQUIRED_KVNET_METRICS
        if name not in documented
    )
    if undocumented:
        print(
            "obs_check: required metrics missing from "
            "docs/OBSERVABILITY.md: " + ", ".join(undocumented)
        )
        return 1
    # doc's regime rule table ↔ the classifier's REGIMES tuple
    from vllm_tgis_adapter_tpu.telemetry.doctor import REGIMES

    doc_regimes = documented_regimes(doc_path)
    if doc_regimes != set(REGIMES):
        print(
            "obs_check: doctor regime rule table diverged from "
            "telemetry.doctor.REGIMES: doc-only "
            f"{sorted(doc_regimes - set(REGIMES))}, code-only "
            f"{sorted(set(REGIMES) - doc_regimes)}"
        )
        return 1
    scraped, state, section_state, doctor_view, timeline = asyncio.run(
        scrape_metrics()
    )
    missing = sorted(
        name for name in documented if name not in scraped
    )
    if missing:
        print(
            "obs_check: metrics documented in docs/OBSERVABILITY.md but "
            "missing from the /metrics scrape:"
        )
        for name in missing:
            print(f"  {name}")
        return 1
    state_missing = [k for k in DEBUG_STATE_KEYS if k not in state]
    replicas = state.get("replicas") or [{}]
    state_missing += [
        f"replicas[0].{k}" for k in REPLICA_KEYS if k not in replicas[0]
    ]
    router = state.get("router") or {}
    state_missing += [
        f"router.{k}" for k in ROUTER_KEYS if k not in router
    ]
    kv_tier = state.get("kv_host_tier") or {}
    state_missing += [
        f"kv_host_tier.{k}" for k in KV_TIER_KEYS if k not in kv_tier
    ]
    tiers = kv_tier.get("tiers") or {}
    state_missing += [
        f"kv_host_tier.tiers.{k}" for k in KV_TIER_TIERS
        if k not in tiers
    ]
    if state_missing:
        print(
            "obs_check: /debug/state is missing documented sections: "
            + ", ".join(state_missing)
        )
        return 1
    # ?section= filtering returned exactly the asked-for sections
    if set(section_state) != {"doctor", "step_timeline"}:
        print(
            "obs_check: ?section=doctor,step_timeline returned "
            f"{sorted(section_state)} instead of exactly the two "
            "requested sections"
        )
        return 1
    # the /debug/doctor view serves the classifier's full shape
    doctor_missing = [
        k for k in ("regimes", "active", "recent", "thresholds")
        if k not in doctor_view
    ]
    if doctor_missing or doctor_view.get("regimes") != list(REGIMES):
        print(
            "obs_check: /debug/doctor is missing keys "
            f"{doctor_missing} or its regime list diverged from "
            "telemetry.doctor.REGIMES"
        )
        return 1
    # the chrome trace is well-formed enough for Perfetto to load
    events = timeline.get("traceEvents")
    if not isinstance(events, list) or not any(
        e.get("ph") == "M" for e in events
    ):
        print(
            "obs_check: /debug/timeline?format=chrome returned no "
            "traceEvents/metadata — not a loadable chrome trace"
        )
        return 1
    print(
        f"obs_check: all {len(documented)} documented metrics present "
        "on /metrics; /debug/state (+?section=), /debug/doctor, and "
        "/debug/timeline serve every documented section"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
