"""Steady-state scenario suites: the closed-loop workload engine.

``tools/chaos_soak.py`` grew a closed-loop scenario engine (fixture
build, in-process AsyncLLMEngine construction, seeded chat/RAG/LoRA
workloads, one-terminal-outcome request driving) to prove recovery
invariants; this module PROMOTES that machinery into reusable
steady-state suites (ROADMAP item 5 — the r03 1043 → r04 1847 → r05
466 tok/s trajectory proved single-number benching cannot police a
quality-affecting surface):

* **Suites** — ``chat`` (unique short prompts, decode-heavy), ``rag``
  (shared system prefix + per-request corpus chunk: the prefix-reuse /
  host-tier shape), ``multi_tenant`` (adapter-churn traffic over a
  small device pool: the S-LoRA shape).  Each run emits per-scenario
  tok/s, TTFT/ITL percentiles, and per-request greedy token streams
  with chosen-token logprobs.

* **The quant gate** (``--quant-gate``, consumed by ``nox -s
  perf_check``'s ``quant`` section): runs every suite twice — a bf16
  KV baseline and the ``--kv-quantization`` engine — at an EQUAL
  synthetic HBM budget (``kv_cache.pages_for_budget`` prices both, so
  the quantized engine's pool really is ~2x the pages: capacity →
  batch size is the mechanism, and the CPU proxy prices it through
  batch occupancy even though the MXU-bandwidth win only shows on
  hardware).  Emitted per scenario: mean/max |Δlogprob| over the
  token-matched prefix of each request (while streams agree both
  engines scored the SAME context, so the delta is the true numeric
  perturbation), the token-match fraction, and the tok/s ratio.

Chaos composition stays in tools/chaos_soak.py, which now imports this
engine and injects faults around it — including quantized-KV seeds.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the shared "system prompt" RAG requests reuse (tiers + prefix paths)
RAG_PREFIX = list(range(400, 424))

#: nothing may outlive this per suite (mirrors the chaos harness bound)
SUITE_BOUND_S = 120.0


def build_fixtures() -> tuple[str, str]:
    """Tiny llama + one live LoRA adapter, built once per process."""
    from tests.fixture_models import (
        build_tiny_llama,
        build_tiny_lora_adapter,
    )

    model_dir = tempfile.mkdtemp(prefix="scenario-model-")
    build_tiny_llama(model_dir)
    adapter_dir = build_tiny_lora_adapter(
        os.path.join(model_dir, "ad-soak"), seed=11, rank=2
    )
    return model_dir, adapter_dir


def build_engine(
    model_dir: str,
    *,
    dp: int = 1,
    watchdog: bool = False,
    roles: tuple = (),
    spec: bool = False,
    kv_quantization: str = "none",
    cache_dtype=None,
    num_blocks: int = 96,
    max_seqs: int = 4,
    prefill_buckets: tuple = (32, 64),
    kv_host_cache_gb: float = 1.0,
    supervised: bool = True,
    enable_prefix_caching: bool = True,
):
    """One production-shaped in-process engine (the closed-loop target
    both the steady-state suites and the chaos soak drive).  Defaults
    reproduce the chaos soak's historical engine exactly."""
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        FrontdoorConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
        SpeculativeConfig,
    )

    mcfg = ModelConfig.from_pretrained(model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16,
            num_blocks=num_blocks,
            cache_dtype=(
                mcfg.dtype if cache_dtype is None else cache_dtype
            ),
            enable_prefix_caching=enable_prefix_caching,
            kv_quantization=kv_quantization,
        ),
        scheduler_config=SchedulerConfig(
            max_num_seqs=max_seqs, prefill_buckets=prefill_buckets
        ),
        parallel_config=ParallelConfig(dp_replicas=dp),
        lora_config=LoRAConfig(enabled=True, max_loras=2,
                               max_lora_rank=2),
        dp_replica_roles=tuple(roles),
        kv_host_cache_gb=kv_host_cache_gb,
        max_engine_restarts=20 if supervised else 0,
        engine_restart_window_s=300.0,
        engine_restart_backoff_s=0.01,
        watchdog_deadline_s=1.0 if watchdog else 0.0,
        watchdog_action="restart",
        frontdoor=FrontdoorConfig(enabled=True),
        speculative=(
            SpeculativeConfig(
                draft_model=model_dir,
                num_speculative_tokens=3,
                draft_model_config=mcfg,
            )
            if spec
            else None
        ),
    )
    return AsyncLLMEngine.from_config(config)


def make_mixed_workload(rng: random.Random, n_requests: int) -> list[dict]:
    """The chaos soak's seeded mixed workload: chat (unique prompts),
    RAG (shared prefix + unique tail), LoRA-tagged — greedy and
    seeded-sampled mixed in."""
    specs = []
    for i in range(n_requests):
        kind = ("chat", "rag", "lora")[i % 3]
        if kind == "rag":
            prompt = RAG_PREFIX + [
                rng.randrange(3, 300)
                for _ in range(rng.randint(4, 12))
            ]
        else:
            prompt = [
                rng.randrange(3, 300)
                for _ in range(rng.randint(6, 20))
            ]
        sampled = rng.random() < 0.34
        specs.append({
            "kind": kind,
            "prompt": prompt,
            "max_tokens": rng.randint(8, 24),
            "temperature": 0.9 if sampled else 0.0,
            "seed": rng.randrange(1, 2**31) if sampled else None,
        })
    return specs


def make_suite_workload(suite: str, rng: random.Random) -> list[dict]:
    """Steady-state suite specs — all greedy with chosen-token logprobs
    (the quality-gate signal), deterministic per suite."""
    specs: list[dict] = []
    if suite == "chat":
        # decode-heavy: short unique prompts, long outputs — the suite
        # whose tok/s prices the capacity → batch-size mechanism (a
        # capped pool preempts mid-decode and pays recompute; 2x pages
        # run the full batch uninterrupted)
        for i in range(16):
            specs.append({
                "kind": "chat",
                "prompt": [3 + (7 * i + j) % 300 for j in range(16)],
                "max_tokens": 48,
            })
    elif suite == "rag":
        # shared system prefix + per-request corpus chunk + unique
        # tail: prefix caching / host-tier reuse in steady state
        for i in range(10):
            specs.append({
                "kind": "rag",
                "prompt": RAG_PREFIX * 2
                + [3 + (11 * i + j) % 300 for j in range(24)],
                "max_tokens": 12,
            })
    elif suite == "multi_tenant":
        # adapter churn: half the traffic rides the live adapter, half
        # the base model — pool swaps + per-row lora_idx in the batch
        for i in range(12):
            specs.append({
                "kind": "lora" if i % 2 == 0 else "chat",
                "prompt": [3 + (13 * i + j) % 300 for j in range(16)],
                "max_tokens": 16,
            })
    else:
        raise ValueError(f"unknown suite {suite!r}")
    for spec in specs:
        spec.setdefault("temperature", 0.0)
        spec.setdefault("seed", None)
        spec.setdefault("logprobs", 1)
    _ = rng  # suites are deterministic; rng reserved for future jitter
    return specs


def _params(spec: dict):
    from vllm_tgis_adapter_tpu.engine.sampling_params import (
        RequestOutputKind,
        SamplingParams,
    )

    return SamplingParams(
        temperature=spec["temperature"],
        seed=spec["seed"],
        max_tokens=spec["max_tokens"],
        ignore_eos=True,
        logprobs=spec.get("logprobs"),
        output_kind=RequestOutputKind.DELTA,
    )


async def run_request(engine, rid: str, spec: dict, lora_req):
    """One DELTA stream to its terminal outcome.  Returns
    ``("ok", [every streamed token, in order])`` or ``("err", exc)`` —
    exactly one of the two, exactly once (the chaos soak's contract)."""
    status, result = await run_timed_request(engine, rid, spec, lora_req)
    if status == "ok":
        return ("ok", result["tokens"])
    return ("err", result)


async def run_timed_request(engine, rid: str, spec: dict, lora_req):
    """``run_request`` plus the steady-state measurements: wall-clock
    TTFT, inter-token gaps, and the chosen-token logprob per streamed
    token (None entries when logprobs were not requested)."""
    toks: list[int] = []
    logprobs: list = []
    itls: list[float] = []
    t0 = time.perf_counter()
    first = None
    last = t0
    try:
        async for out in engine.generate(
            prompt=None,
            sampling_params=_params(spec),
            request_id=rid,
            prompt_token_ids=list(spec["prompt"]),
            lora_request=lora_req if spec["kind"] == "lora" else None,
        ):
            now = time.perf_counter()
            seq_out = out.outputs[0]
            new = list(seq_out.token_ids)
            if new:
                if first is None:
                    first = now
                else:
                    itls.append((now - last) / len(new))
                last = now
            toks.extend(new)
            for tbl, tok in zip(seq_out.logprobs or [], new):
                entry = tbl.get(tok) if hasattr(tbl, "get") else None
                logprobs.append(
                    getattr(entry, "logprob", None)
                    if entry is not None
                    else None
                )
        return ("ok", {
            "tokens": toks,
            "logprobs": logprobs,
            "ttft_s": (first - t0) if first is not None else None,
            "itls_s": itls,
            "wall_s": time.perf_counter() - t0,
        })
    except BaseException as e:  # noqa: BLE001 — the outcome IS the result
        return ("err", e)


def _pct(values: list[float], q: float) -> float | None:
    if not values:
        return None
    values = sorted(values)
    idx = min(len(values) - 1, int(round(q * (len(values) - 1))))
    return values[idx]


async def run_suite(engine, specs: list[dict], lora_req, tag: str) -> dict:
    """Drive one suite closed-loop (all requests concurrent) and fold
    the per-request measurements into the scenario line."""
    t0 = time.perf_counter()
    tasks = [
        asyncio.create_task(run_timed_request(
            engine, f"{tag}-{i}", spec, lora_req
        ))
        for i, spec in enumerate(specs)
    ]
    done = await asyncio.wait_for(asyncio.gather(*tasks), SUITE_BOUND_S)
    wall = time.perf_counter() - t0
    requests = []
    ttfts: list[float] = []
    itls: list[float] = []
    out_tokens = 0
    for status, result in done:
        if status != "ok":
            raise RuntimeError(f"suite {tag} request failed: {result!r}")
        requests.append(result)
        out_tokens += len(result["tokens"])
        if result["ttft_s"] is not None:
            ttfts.append(result["ttft_s"])
        itls.extend(result["itls_s"])
    return {
        "requests": requests,
        "tok_per_s": round(out_tokens / max(wall, 1e-9), 1),
        "output_tokens": out_tokens,
        "wall_s": round(wall, 3),
        "ttft_ms_p50": _round_ms(_pct(ttfts, 0.50)),
        "ttft_ms_p99": _round_ms(_pct(ttfts, 0.99)),
        "itl_ms_p50": _round_ms(_pct(itls, 0.50)),
        "itl_ms_p99": _round_ms(_pct(itls, 0.99)),
    }


def _round_ms(seconds: float | None) -> float | None:
    return None if seconds is None else round(seconds * 1e3, 3)


def logprob_delta(base: dict, quant: dict) -> dict:
    """Per-token quality deltas over the token-MATCHED prefix of every
    request pair: while the streams agree, both engines scored the same
    context, so |Δlogprob| is the pure numeric perturbation of the
    quantized KV read.  ``token_match_frac`` reports how far greedy
    streams stayed identical."""
    deltas: list[float] = []
    matched = 0
    total = 0
    for rb, rq in zip(base["requests"], quant["requests"]):
        total += max(len(rb["tokens"]), len(rq["tokens"]))
        for tb, tq, lb, lq in zip(
            rb["tokens"], rq["tokens"], rb["logprobs"], rq["logprobs"]
        ):
            if tb != tq:
                break
            matched += 1
            if lb is not None and lq is not None:
                deltas.append(abs(lb - lq))
    return {
        "mean_abs_logprob_delta": (
            round(statistics.fmean(deltas), 5) if deltas else None
        ),
        "max_abs_logprob_delta": (
            round(max(deltas), 5) if deltas else None
        ),
        "token_match_frac": round(matched / max(total, 1), 4),
        "compared_tokens": len(deltas),
    }


# ------------------------------------------------------------ quant gate

SUITES = ("chat", "rag", "multi_tenant")


def _gate_config(model_dir: str, kvq: str, num_blocks: int):
    """EngineConfig shell used ONLY for capacity pricing (never booted)."""
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )

    mcfg = ModelConfig.from_pretrained(model_dir, dtype="float32")
    return EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16, num_blocks=num_blocks,
            cache_dtype=jnp.bfloat16, kv_quantization=kvq,
        ),
        scheduler_config=SchedulerConfig(max_num_seqs=16),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )


async def quant_gate(model_dir: str, adapter_dir: str, scheme: str) -> dict:
    """The perf_check ``quant`` section's measurement: every suite on a
    bf16-KV baseline AND the quantized engine at an EQUAL synthetic HBM
    budget.  The budget is sized to ~55% of the chat suite's KV working
    set, so the baseline pool caps concurrency while the ~2x quantized
    pool fits the whole batch — capacity → batch size, priced honestly
    by the CPU proxy through batch occupancy."""
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.kv_cache import (
        pages_for_budget,
        per_block_bytes,
    )

    # chat working set: 16 requests x ceil((24 prompt + 32 out) / 16)
    chat_specs = make_suite_workload("chat", random.Random(0))
    pages_per_seq = -(-max(
        len(s["prompt"]) + s["max_tokens"] for s in chat_specs
    ) // 16)
    working_set = len(chat_specs) * pages_per_seq
    base_cfg = _gate_config(model_dir, "none", 1)
    budget = int(0.55 * working_set * per_block_bytes(base_cfg))
    base_blocks = pages_for_budget(base_cfg, budget)
    quant_blocks = pages_for_budget(
        _gate_config(model_dir, scheme, 1), budget
    )
    capacity = {
        "budget_bytes": budget,
        "bf16_blocks": base_blocks,
        "quant_blocks": quant_blocks,
        "ratio": round(quant_blocks / max(base_blocks, 1), 3),
    }

    # CPU-proxy fidelity (bench.py's BENCH_SYNC_DISPATCH discipline):
    # async CPU dispatch funnels through shared machinery and jitters
    # the closed-loop timings; synchronous dispatch behaves like an
    # accelerator stream
    import jax

    jax.config.update("jax_cpu_enable_async_dispatch", False)

    async def run_side(kvq: str, num_blocks: int, cache_dtype) -> dict:
        suites = {}
        for suite in SUITES:
            # the chat capacity gate must isolate the capacity → batch
            # mechanism: prefix caching / the host tier would mask the
            # capped pool by serving the measured pass from reuse.  The
            # rag and multi_tenant suites keep both ON — reuse under
            # quantized pages is exactly what they steady-state.
            chat = suite == "chat"
            engine = build_engine(
                model_dir,
                kv_quantization=kvq,
                cache_dtype=cache_dtype,
                num_blocks=num_blocks,
                max_seqs=16,
                prefill_buckets=(32, 64, 128),
                supervised=False,
                enable_prefix_caching=not chat,
                kv_host_cache_gb=0.0 if chat else 1.0,
            )
            try:
                lora_req = (
                    await engine.engine.lora_manager.load_lora_adapter(
                        "ad-soak", adapter_dir
                    )
                )
                specs = make_suite_workload(suite, random.Random(0))
                # warm pass compiles every shape; the measured pass is
                # steady-state (the r05 lesson: never time a compile)
                await run_suite(
                    engine, specs, lora_req, f"warm-{kvq}-{suite}"
                )
                suites[suite] = await run_suite(
                    engine, specs, lora_req, f"{kvq}-{suite}"
                )
            finally:
                await engine.stop()
        return suites

    base = await run_side("none", base_blocks, jnp.bfloat16)
    quant = await run_side(scheme, quant_blocks, None)

    scenarios = {}
    worst_delta = 0.0
    for suite in SUITES:
        quality = logprob_delta(base[suite], quant[suite])
        if quality["mean_abs_logprob_delta"] is not None:
            worst_delta = max(
                worst_delta, quality["mean_abs_logprob_delta"]
            )
        scenarios[suite] = {
            "bf16_tok_per_s": base[suite]["tok_per_s"],
            "quant_tok_per_s": quant[suite]["tok_per_s"],
            "tok_per_s_ratio": round(
                quant[suite]["tok_per_s"]
                / max(base[suite]["tok_per_s"], 1e-9),
                3,
            ),
            "bf16_ttft_ms_p50": base[suite]["ttft_ms_p50"],
            "quant_ttft_ms_p50": quant[suite]["ttft_ms_p50"],
            "bf16_itl_ms_p50": base[suite]["itl_ms_p50"],
            "quant_itl_ms_p50": quant[suite]["itl_ms_p50"],
            "quant_itl_ms_p99": quant[suite]["itl_ms_p99"],
            **quality,
        }
    try:  # publish the quality signal (docs/OBSERVABILITY.md row)
        from vllm_tgis_adapter_tpu import metrics

        metrics.quant_logprob_delta.set(worst_delta)
    except Exception:  # noqa: BLE001 — telemetry must not fail the gate
        pass
    return {
        "kind": "quant",
        "scheme": scheme,
        "capacity": capacity,
        "scenarios": scenarios,
    }


async def steady_state(model_dir: str, adapter_dir: str) -> dict:
    """Plain steady-state run of every suite on the default engine —
    the non-gating inspection entry point."""
    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=16,
        prefill_buckets=(32, 64, 128), supervised=False,
    )
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        suites = {}
        for suite in SUITES:
            specs = make_suite_workload(suite, random.Random(0))
            await run_suite(engine, specs, lora_req, f"warm-{suite}")
            line = await run_suite(engine, specs, lora_req, suite)
            line.pop("requests")
            suites[suite] = line
        return {"kind": "scenarios", "suites": suites}
    finally:
        await engine.stop()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quant-gate", action="store_true",
                        help="run the bf16-vs-quantized comparison and "
                             "print one JSON line (perf_check `quant`)")
    parser.add_argument("--scheme", default="int8",
                        choices=["int8", "fp8"],
                        help="--kv-quantization scheme under test")
    args = parser.parse_args(argv)

    model_dir, adapter_dir = build_fixtures()
    if args.quant_gate:
        line = asyncio.run(quant_gate(model_dir, adapter_dir, args.scheme))
    else:
        line = asyncio.run(steady_state(model_dir, adapter_dir))
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
