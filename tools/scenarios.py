"""Steady-state scenario suites: the closed-loop workload engine.

``tools/chaos_soak.py`` grew a closed-loop scenario engine (fixture
build, in-process AsyncLLMEngine construction, seeded chat/RAG/LoRA
workloads, one-terminal-outcome request driving) to prove recovery
invariants; this module PROMOTES that machinery into reusable
steady-state suites (ROADMAP item 5 — the r03 1043 → r04 1847 → r05
466 tok/s trajectory proved single-number benching cannot police a
quality-affecting surface):

* **Suites** — ``chat`` (unique short prompts, decode-heavy), ``rag``
  (shared system prefix + per-request corpus chunk: the prefix-reuse /
  host-tier shape), ``multi_tenant`` (adapter-churn traffic over a
  small device pool: the S-LoRA shape).  Each run emits per-scenario
  tok/s, TTFT/ITL percentiles, and per-request greedy token streams
  with chosen-token logprobs.

* **The quant gate** (``--quant-gate``, consumed by ``nox -s
  perf_check``'s ``quant`` section): runs every suite twice — a bf16
  KV baseline and the ``--kv-quantization`` engine — at an EQUAL
  synthetic HBM budget (``kv_cache.pages_for_budget`` prices both, so
  the quantized engine's pool really is ~2x the pages: capacity →
  batch size is the mechanism, and the CPU proxy prices it through
  batch occupancy even though the MXU-bandwidth win only shows on
  hardware).  Emitted per scenario: mean/max |Δlogprob| over the
  token-matched prefix of each request (while streams agree both
  engines scored the SAME context, so the delta is the true numeric
  perturbation), the token-match fraction, and the tok/s ratio.

Chaos composition stays in tools/chaos_soak.py, which now imports this
engine and injects faults around it — including quantized-KV seeds.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import statistics
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# steady-state suites double as invariant tests (engine/sanitizer.py):
# accounting drift fails the suite at the drifting step
os.environ.setdefault("TGIS_TPU_SANITIZE", "1")

#: the shared "system prompt" RAG requests reuse (tiers + prefix paths)
RAG_PREFIX = list(range(400, 424))

#: nothing may outlive this per suite (mirrors the chaos harness bound)
SUITE_BOUND_S = 120.0


def build_fixtures() -> tuple[str, str]:
    """Tiny llama + one live LoRA adapter, built once per process."""
    from tests.fixture_models import (
        build_tiny_llama,
        build_tiny_lora_adapter,
    )

    model_dir = tempfile.mkdtemp(prefix="scenario-model-")
    build_tiny_llama(model_dir)
    adapter_dir = build_tiny_lora_adapter(
        os.path.join(model_dir, "ad-soak"), seed=11, rank=2
    )
    return model_dir, adapter_dir


#: the unified gate's model arch (bench.py's "small" dp-proxy shape):
#: enough per-token device work that recompute-vs-promote pricing is
#: dominated by model compute, not host fixed costs — the tiny fixture
#: recomputes a 240-token prefill in ~the promotion machinery's fixed
#: overhead, which would price the tiers as worthless when the real
#: mechanism (skip quadratic prefill, restore linear pages) is exactly
#: what hardware pays
SMALL_ARCH = {
    "vocab_size": 512,
    "hidden_size": 256,
    "intermediate_size": 512,
    "num_hidden_layers": 4,
    "num_attention_heads": 8,
    "num_key_value_heads": 4,
    "head_dim": 32,
}


def build_small_llama(path: str) -> str:
    """HF-format checkpoint at SMALL_ARCH (tokenizer + config +
    deterministic safetensors via the shared fixture writer)."""
    from tests.fixture_models import (
        build_tokenizer,
        write_llama_safetensors,
    )

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)
    build_tokenizer(path, vocab_size=SMALL_ARCH["vocab_size"])
    cfg = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "max_position_embeddings": 512,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-6,
        "tie_word_embeddings": False,
        "bos_token_id": 1,
        "eos_token_id": 2,
        "torch_dtype": "float32",
        **{
            k: SMALL_ARCH[k]
            for k in ("vocab_size", "hidden_size", "intermediate_size",
                      "num_hidden_layers", "num_attention_heads",
                      "num_key_value_heads", "head_dim")
        },
    }
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)
    write_llama_safetensors(
        path,
        vocab_size=SMALL_ARCH["vocab_size"],
        hidden_size=SMALL_ARCH["hidden_size"],
        intermediate_size=SMALL_ARCH["intermediate_size"],
        num_layers=SMALL_ARCH["num_hidden_layers"],
        num_heads=SMALL_ARCH["num_attention_heads"],
        num_kv_heads=SMALL_ARCH["num_key_value_heads"],
        head_dim=SMALL_ARCH["head_dim"],
    )
    return str(out)


def build_engine(
    model_dir: str,
    *,
    dp: int = 1,
    watchdog: bool = False,
    roles: tuple = (),
    spec: bool = False,
    kv_quantization: str = "none",
    cache_dtype=None,
    num_blocks: int = 96,
    max_seqs: int = 4,
    prefill_buckets: tuple = (32, 64),
    kv_host_cache_gb: float = 1.0,
    kv_disk_cache_gb: float = 0.0,
    kv_disk_cache_dir: str | None = None,
    supervised: bool = True,
    enable_prefix_caching: bool = True,
    max_loras: int = 2,
    max_lora_rank: int = 2,
    frontdoor=None,
    slo_config: str | None = None,
    ledger_log: str | None = None,
    capture_trace: str | None = None,
    kvnet_listen: str | None = None,
    kvnet_peers: tuple = (),
    kvnet_node_id: str | None = None,
    kvnet_timeout_s: float = 5.0,
):
    """One production-shaped in-process engine (the closed-loop target
    both the steady-state suites and the chaos soak drive).  Defaults
    reproduce the chaos soak's historical engine exactly."""
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        FrontdoorConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
        SpeculativeConfig,
    )

    mcfg = ModelConfig.from_pretrained(model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16,
            num_blocks=num_blocks,
            cache_dtype=(
                mcfg.dtype if cache_dtype is None else cache_dtype
            ),
            enable_prefix_caching=enable_prefix_caching,
            kv_quantization=kv_quantization,
        ),
        scheduler_config=SchedulerConfig(
            max_num_seqs=max_seqs, prefill_buckets=prefill_buckets
        ),
        parallel_config=ParallelConfig(dp_replicas=dp),
        lora_config=LoRAConfig(enabled=True, max_loras=max_loras,
                               max_lora_rank=max_lora_rank),
        dp_replica_roles=tuple(roles),
        kv_host_cache_gb=kv_host_cache_gb,
        kv_disk_cache_gb=kv_disk_cache_gb,
        kv_disk_cache_dir=kv_disk_cache_dir,
        kvnet_listen=kvnet_listen,
        kvnet_peers=tuple(kvnet_peers),
        kvnet_node_id=kvnet_node_id,
        kvnet_timeout_s=kvnet_timeout_s,
        max_engine_restarts=20 if supervised else 0,
        engine_restart_window_s=300.0,
        engine_restart_backoff_s=0.01,
        watchdog_deadline_s=1.0 if watchdog else 0.0,
        watchdog_action="restart",
        slo_config=slo_config,
        ledger_log=ledger_log,
        capture_trace=capture_trace,
        frontdoor=(
            frontdoor if frontdoor is not None
            else FrontdoorConfig(enabled=True)
        ),
        speculative=(
            SpeculativeConfig(
                draft_model=model_dir,
                num_speculative_tokens=3,
                draft_model_config=mcfg,
            )
            if spec
            else None
        ),
    )
    return AsyncLLMEngine.from_config(config)


def make_mixed_workload(rng: random.Random, n_requests: int) -> list[dict]:
    """The chaos soak's seeded mixed workload: chat (unique prompts),
    RAG (shared prefix + unique tail), LoRA-tagged — greedy and
    seeded-sampled mixed in."""
    specs = []
    for i in range(n_requests):
        kind = ("chat", "rag", "lora")[i % 3]
        if kind == "rag":
            prompt = RAG_PREFIX + [
                rng.randrange(3, 300)
                for _ in range(rng.randint(4, 12))
            ]
        else:
            prompt = [
                rng.randrange(3, 300)
                for _ in range(rng.randint(6, 20))
            ]
        sampled = rng.random() < 0.34
        specs.append({
            "kind": kind,
            "prompt": prompt,
            "max_tokens": rng.randint(8, 24),
            "temperature": 0.9 if sampled else 0.0,
            "seed": rng.randrange(1, 2**31) if sampled else None,
        })
    return specs


def make_suite_workload(suite: str, rng: random.Random) -> list[dict]:
    """Steady-state suite specs — all greedy with chosen-token logprobs
    (the quality-gate signal), deterministic per suite."""
    specs: list[dict] = []
    if suite == "chat":
        # decode-heavy: short unique prompts, long outputs — the suite
        # whose tok/s prices the capacity → batch-size mechanism (a
        # capped pool preempts mid-decode and pays recompute; 2x pages
        # run the full batch uninterrupted)
        for i in range(16):
            specs.append({
                "kind": "chat",
                "prompt": [3 + (7 * i + j) % 300 for j in range(16)],
                "max_tokens": 48,
            })
    elif suite == "rag":
        # shared system prefix + per-request corpus chunk + unique
        # tail: prefix caching / host-tier reuse in steady state
        for i in range(10):
            specs.append({
                "kind": "rag",
                "prompt": RAG_PREFIX * 2
                + [3 + (11 * i + j) % 300 for j in range(24)],
                "max_tokens": 12,
            })
    elif suite == "multi_tenant":
        # adapter churn: half the traffic rides the live adapter, half
        # the base model — pool swaps + per-row lora_idx in the batch
        for i in range(12):
            specs.append({
                "kind": "lora" if i % 2 == 0 else "chat",
                "prompt": [3 + (13 * i + j) % 300 for j in range(16)],
                "max_tokens": 16,
            })
    else:
        raise ValueError(f"unknown suite {suite!r}")
    for spec in specs:
        spec.setdefault("temperature", 0.0)
        spec.setdefault("seed", None)
        spec.setdefault("logprobs", 1)
    _ = rng  # suites are deterministic; rng reserved for future jitter
    return specs


def _params(spec: dict):
    from vllm_tgis_adapter_tpu.engine.sampling_params import (
        RequestOutputKind,
        SamplingParams,
    )

    return SamplingParams(
        temperature=spec["temperature"],
        seed=spec["seed"],
        max_tokens=spec["max_tokens"],
        ignore_eos=True,
        logprobs=spec.get("logprobs"),
        output_kind=RequestOutputKind.DELTA,
    )


async def run_request(engine, rid: str, spec: dict, lora_req):
    """One DELTA stream to its terminal outcome.  Returns
    ``("ok", [every streamed token, in order])`` or ``("err", exc)`` —
    exactly one of the two, exactly once (the chaos soak's contract)."""
    status, result = await run_timed_request(engine, rid, spec, lora_req)
    if status == "ok":
        return ("ok", result["tokens"])
    return ("err", result)


async def run_timed_request(engine, rid: str, spec: dict, lora_req):
    """``run_request`` plus the steady-state measurements: wall-clock
    TTFT, inter-token gaps, and the chosen-token logprob per streamed
    token (None entries when logprobs were not requested)."""
    toks: list[int] = []
    logprobs: list = []
    itls: list[float] = []
    t0 = time.perf_counter()
    first = None
    last = t0
    try:
        async for out in engine.generate(
            prompt=None,
            sampling_params=_params(spec),
            request_id=rid,
            prompt_token_ids=list(spec["prompt"]),
            lora_request=lora_req if spec["kind"] == "lora" else None,
            tenant_id=spec.get("tenant"),
        ):
            now = time.perf_counter()
            seq_out = out.outputs[0]
            new = list(seq_out.token_ids)
            if new:
                if first is None:
                    first = now
                else:
                    itls.append((now - last) / len(new))
                last = now
            toks.extend(new)
            for tbl, tok in zip(seq_out.logprobs or [], new):
                entry = tbl.get(tok) if hasattr(tbl, "get") else None
                logprobs.append(
                    getattr(entry, "logprob", None)
                    if entry is not None
                    else None
                )
        return ("ok", {
            "tokens": toks,
            "logprobs": logprobs,
            "ttft_s": (first - t0) if first is not None else None,
            "itls_s": itls,
            "wall_s": time.perf_counter() - t0,
        })
    except BaseException as e:  # noqa: BLE001 — the outcome IS the result
        return ("err", e)


def _pct(values: list[float], q: float) -> float | None:
    if not values:
        return None
    values = sorted(values)
    idx = min(len(values) - 1, int(round(q * (len(values) - 1))))
    return values[idx]


def mfu_stamp(tok_per_s: float, mcfg) -> dict:
    """MFU next to every tok/s number (ISSUE 14 satellite): achieved
    model FLOP/s over the accelerator's peak.  The math lives in
    telemetry/mfu.py now — the SAME numerator feeds the live
    ``mfu{replica}`` gauges, so the bench and the gauges cannot drift.
    The peak comes from ``TGIS_PEAK_TFLOPS`` (a per-chip spec the
    operator sets — e.g. 197 for v5e bf16); without it the stamp still
    reports the achieved model TFLOP/s so hardware runs can derive MFU
    post-hoc, and ``mfu`` is None (the CPU proxy has no meaningful
    peak)."""
    from vllm_tgis_adapter_tpu.telemetry.mfu import (
        achieved_tflops,
        peak_tflops,
    )

    achieved = achieved_tflops(tok_per_s, mcfg)
    peak = peak_tflops()
    return {
        "model_tflops_per_s": round(achieved, 6),
        "mfu": round(achieved / peak, 6) if peak > 0 else None,
    }


async def run_suite(engine, specs: list[dict], lora_req, tag: str,
                    allow_sheds: bool = False) -> dict:
    """Drive one suite closed-loop (all requests concurrent) and fold
    the per-request measurements into the scenario line.  The MFU
    stamp rides next to tok/s (ISSUE 14 satellite).  With
    ``allow_sheds`` admission sheds are an expected OUTCOME (bursty /
    drain suites) and are folded into per-tenant shed counts instead
    of failing the suite."""
    from vllm_tgis_adapter_tpu.frontdoor.errors import AdmissionShedError

    t0 = time.perf_counter()
    tasks = [
        asyncio.create_task(run_timed_request(
            engine, f"{tag}-{i}", spec, lora_req
        ))
        for i, spec in enumerate(specs)
    ]
    done = await asyncio.wait_for(asyncio.gather(*tasks), SUITE_BOUND_S)
    wall = time.perf_counter() - t0
    requests = []
    ttfts: list[float] = []
    itls: list[float] = []
    out_tokens = 0
    sheds: list[dict] = []
    for spec, (status, result) in zip(specs, done):
        if status != "ok":
            if allow_sheds and isinstance(result, AdmissionShedError):
                sheds.append({
                    "tenant": spec.get("tenant") or "default",
                    "reason": result.reason,
                })
                continue
            raise RuntimeError(f"suite {tag} request failed: {result!r}")
        result["tenant"] = spec.get("tenant") or "default"
        requests.append(result)
        out_tokens += len(result["tokens"])
        if result["ttft_s"] is not None:
            ttfts.append(result["ttft_s"])
        itls.extend(result["itls_s"])
    tok_per_s = round(out_tokens / max(wall, 1e-9), 1)
    return {
        "requests": requests,
        "sheds": sheds,
        "tok_per_s": tok_per_s,
        **mfu_stamp(tok_per_s, engine.engine.config.model_config),
        "output_tokens": out_tokens,
        "wall_s": round(wall, 3),
        "ttft_ms_p50": _round_ms(_pct(ttfts, 0.50)),
        "ttft_ms_p99": _round_ms(_pct(ttfts, 0.99)),
        "itl_ms_p50": _round_ms(_pct(itls, 0.50)),
        "itl_ms_p99": _round_ms(_pct(itls, 0.99)),
    }


def _round_ms(seconds: float | None) -> float | None:
    return None if seconds is None else round(seconds * 1e3, 3)


def logprob_delta(base: dict, quant: dict) -> dict:
    """Per-token quality deltas over the token-MATCHED prefix of every
    request pair: while the streams agree, both engines scored the same
    context, so |Δlogprob| is the pure numeric perturbation of the
    quantized KV read.  ``token_match_frac`` reports how far greedy
    streams stayed identical."""
    deltas: list[float] = []
    matched = 0
    total = 0
    for rb, rq in zip(base["requests"], quant["requests"]):
        total += max(len(rb["tokens"]), len(rq["tokens"]))
        for tb, tq, lb, lq in zip(
            rb["tokens"], rq["tokens"], rb["logprobs"], rq["logprobs"]
        ):
            if tb != tq:
                break
            matched += 1
            if lb is not None and lq is not None:
                deltas.append(abs(lb - lq))
    return {
        "mean_abs_logprob_delta": (
            round(statistics.fmean(deltas), 5) if deltas else None
        ),
        "max_abs_logprob_delta": (
            round(max(deltas), 5) if deltas else None
        ),
        "token_match_frac": round(matched / max(total, 1), 4),
        "compared_tokens": len(deltas),
    }


# ------------------------------------------------------------ quant gate

SUITES = ("chat", "rag", "multi_tenant")


def _gate_config(model_dir: str, kvq: str, num_blocks: int):
    """EngineConfig shell used ONLY for capacity pricing (never booted)."""
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )

    mcfg = ModelConfig.from_pretrained(model_dir, dtype="float32")
    return EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16, num_blocks=num_blocks,
            cache_dtype=jnp.bfloat16, kv_quantization=kvq,
        ),
        scheduler_config=SchedulerConfig(max_num_seqs=16),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )


async def quant_gate(model_dir: str, adapter_dir: str, scheme: str) -> dict:
    """The perf_check ``quant`` section's measurement: every suite on a
    bf16-KV baseline AND the quantized engine at an EQUAL synthetic HBM
    budget.  The budget is sized to ~55% of the chat suite's KV working
    set, so the baseline pool caps concurrency while the ~2x quantized
    pool fits the whole batch — capacity → batch size, priced honestly
    by the CPU proxy through batch occupancy."""
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.kv_cache import (
        pages_for_budget,
        per_block_bytes,
    )

    # chat working set: 16 requests x ceil((24 prompt + 32 out) / 16)
    chat_specs = make_suite_workload("chat", random.Random(0))
    pages_per_seq = -(-max(
        len(s["prompt"]) + s["max_tokens"] for s in chat_specs
    ) // 16)
    working_set = len(chat_specs) * pages_per_seq
    base_cfg = _gate_config(model_dir, "none", 1)
    budget = int(0.55 * working_set * per_block_bytes(base_cfg))
    base_blocks = pages_for_budget(base_cfg, budget)
    quant_blocks = pages_for_budget(
        _gate_config(model_dir, scheme, 1), budget
    )
    capacity = {
        "budget_bytes": budget,
        "bf16_blocks": base_blocks,
        "quant_blocks": quant_blocks,
        "ratio": round(quant_blocks / max(base_blocks, 1), 3),
    }

    # CPU-proxy fidelity (bench.py's BENCH_SYNC_DISPATCH discipline):
    # async CPU dispatch funnels through shared machinery and jitters
    # the closed-loop timings; synchronous dispatch behaves like an
    # accelerator stream
    import jax

    jax.config.update("jax_cpu_enable_async_dispatch", False)

    async def run_side(kvq: str, num_blocks: int, cache_dtype) -> dict:
        suites = {}
        for suite in SUITES:
            # the chat capacity gate must isolate the capacity → batch
            # mechanism: prefix caching / the host tier would mask the
            # capped pool by serving the measured pass from reuse.  The
            # rag and multi_tenant suites keep both ON — reuse under
            # quantized pages is exactly what they steady-state.
            chat = suite == "chat"
            engine = build_engine(
                model_dir,
                kv_quantization=kvq,
                cache_dtype=cache_dtype,
                num_blocks=num_blocks,
                max_seqs=16,
                prefill_buckets=(32, 64, 128),
                supervised=False,
                enable_prefix_caching=not chat,
                kv_host_cache_gb=0.0 if chat else 1.0,
            )
            try:
                lora_req = (
                    await engine.engine.lora_manager.load_lora_adapter(
                        "ad-soak", adapter_dir
                    )
                )
                specs = make_suite_workload(suite, random.Random(0))
                # warm pass compiles every shape; the measured pass is
                # steady-state (the r05 lesson: never time a compile)
                await run_suite(
                    engine, specs, lora_req, f"warm-{kvq}-{suite}"
                )
                suites[suite] = await run_suite(
                    engine, specs, lora_req, f"{kvq}-{suite}"
                )
            finally:
                await engine.stop()
        return suites

    base = await run_side("none", base_blocks, jnp.bfloat16)
    quant = await run_side(scheme, quant_blocks, None)

    scenarios = {}
    worst_delta = 0.0
    for suite in SUITES:
        quality = logprob_delta(base[suite], quant[suite])
        if quality["mean_abs_logprob_delta"] is not None:
            worst_delta = max(
                worst_delta, quality["mean_abs_logprob_delta"]
            )
        scenarios[suite] = {
            "bf16_tok_per_s": base[suite]["tok_per_s"],
            "quant_tok_per_s": quant[suite]["tok_per_s"],
            "tok_per_s_ratio": round(
                quant[suite]["tok_per_s"]
                / max(base[suite]["tok_per_s"], 1e-9),
                3,
            ),
            "bf16_ttft_ms_p50": base[suite]["ttft_ms_p50"],
            "quant_ttft_ms_p50": quant[suite]["ttft_ms_p50"],
            "bf16_itl_ms_p50": base[suite]["itl_ms_p50"],
            "quant_itl_ms_p50": quant[suite]["itl_ms_p50"],
            "quant_itl_ms_p99": quant[suite]["itl_ms_p99"],
            **quality,
        }
    try:  # publish the quality signal (docs/OBSERVABILITY.md row)
        from vllm_tgis_adapter_tpu import metrics

        metrics.quant_logprob_delta.set(worst_delta)
    except Exception:  # noqa: BLE001 — telemetry must not fail the gate
        pass
    return {
        "kind": "quant",
        "scheme": scheme,
        "capacity": capacity,
        "scenarios": scenarios,
    }


# ------------------------------------------- bursty / drain suites (5b)


def _tenant_stats(line: dict, weights: dict) -> dict:
    """Per-tenant sheds + served-token shares and the WFQ share error
    (ISSUE 14 satellite): served share vs weight share over the
    tenants that offered load — 0 = perfectly weighted service."""
    tenants: dict[str, dict] = {}
    for req in line["requests"]:
        t = tenants.setdefault(
            req["tenant"], {"ok": 0, "tokens": 0, "sheds": {}}
        )
        t["ok"] += 1
        t["tokens"] += len(req["tokens"])
    for shed in line["sheds"]:
        t = tenants.setdefault(
            shed["tenant"], {"ok": 0, "tokens": 0, "sheds": {}}
        )
        t["sheds"][shed["reason"]] = (
            t["sheds"].get(shed["reason"], 0) + 1
        )
    total_tokens = sum(t["tokens"] for t in tenants.values())
    total_weight = sum(weights.get(name, 1.0) for name in tenants)
    share_error = 0.0
    for name, t in tenants.items():
        actual = t["tokens"] / max(total_tokens, 1)
        expected = weights.get(name, 1.0) / max(total_weight, 1e-9)
        t["token_share"] = round(actual, 4)
        t["weight_share"] = round(expected, 4)
        share_error += abs(actual - expected)
    return {
        "per_tenant": tenants,
        "total_sheds": len(line["sheds"]),
        "wfq_share_error": round(share_error / 2, 4),
    }


async def bursty_multitenant(model_dir: str, adapter_dir: str) -> dict:
    """Bursty multi-tenant suite: three tenants (one weighted 4x, one
    1x, one riding the live adapter) fire synchronized bursts past the
    bounded admission queue — the shape that exercises WFQ ordering,
    per-tenant shedding, and adapter churn TOGETHER.  Emits shed and
    fairness stats next to tok/s + MFU."""
    from vllm_tgis_adapter_tpu.engine.config import FrontdoorConfig

    weights = {"t-heavy": 4.0, "t-light": 1.0, "t-lora": 1.0}
    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=4,
        prefill_buckets=(32, 64, 128), supervised=False,
        frontdoor=FrontdoorConfig(
            enabled=True,
            max_waiting_requests=14,
            tenant_weights=tuple(weights.items()),
        ),
    )
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        specs: list[dict] = []
        for burst in range(3):
            for i in range(8):
                tenant = ("t-heavy", "t-heavy", "t-light", "t-lora")[
                    i % 4
                ]
                specs.append({
                    "kind": "lora" if tenant == "t-lora" else "chat",
                    "tenant": tenant,
                    "prompt": [
                        3 + (17 * (burst * 8 + i) + j) % 300
                        for j in range(16)
                    ],
                    "max_tokens": 16,
                    "temperature": 0.0,
                    "seed": None,
                })
        # warm pass compiles every shape (no bursts, tiny)
        await run_suite(
            engine, specs[:4], lora_req, "warm-bursty", allow_sheds=True
        )
        line = await run_suite(
            engine, specs, lora_req, "bursty", allow_sheds=True
        )
        stats = _tenant_stats(line, weights)
        line.pop("requests")
        return {"kind": "bursty_multitenant", **line, **stats}
    finally:
        await engine.stop()


async def drain_under_load(model_dir: str, adapter_dir: str) -> dict:
    """Drain-under-load suite: begin a graceful drain while a full
    batch is mid-decode, then offer more traffic.  In-flight requests
    must FINISH (zero lost outputs), post-drain arrivals must shed
    with the typed ``draining`` reason — the SIGTERM story in
    steady-state form."""
    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=4,
        prefill_buckets=(32, 64, 128), supervised=False,
    )
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        pre_specs = [{
            "kind": "chat",
            "prompt": [3 + (7 * i + j) % 300 for j in range(16)],
            "max_tokens": 32,
            "temperature": 0.0,
            "seed": None,
        } for i in range(8)]
        # warm the shapes so drain timing is steady-state
        await run_suite(engine, pre_specs[:2], lora_req, "warm-drain")
        t0 = time.perf_counter()
        tasks = [
            asyncio.create_task(run_timed_request(
                engine, f"drain-pre-{i}", spec, lora_req
            ))
            for i, spec in enumerate(pre_specs)
        ]
        # let the batch reach decode, then stop admitting
        await asyncio.sleep(0.5)
        parked_shed = engine.frontdoor.begin_drain()
        post_specs = [{
            "kind": "chat",
            "prompt": [5 + (11 * i + j) % 300 for j in range(12)],
            "max_tokens": 8,
            "temperature": 0.0,
            "seed": None,
        } for i in range(4)]
        post = [
            asyncio.create_task(run_timed_request(
                engine, f"drain-post-{i}", spec, lora_req
            ))
            for i, spec in enumerate(post_specs)
        ]
        done = await asyncio.wait_for(
            asyncio.gather(*tasks), SUITE_BOUND_S
        )
        post_done = await asyncio.wait_for(
            asyncio.gather(*post), SUITE_BOUND_S
        )
        wall = time.perf_counter() - t0
        from vllm_tgis_adapter_tpu.frontdoor.errors import (
            AdmissionShedError,
        )

        completed = [
            r for s, r in done
            if s == "ok" and len(r["tokens"]) == 32
        ]
        post_sheds = [
            r for s, r in post_done
            if s != "ok"
            and isinstance(r, AdmissionShedError)
            and r.reason == "draining"
        ]
        out_tokens = sum(len(r["tokens"]) for _, r in done if _ == "ok")
        tok_per_s = round(out_tokens / max(wall, 1e-9), 1)
        return {
            "kind": "drain_under_load",
            "in_flight": len(pre_specs),
            "completed_full": len(completed),
            "parked_shed_at_drain": parked_shed,
            "post_drain_offered": len(post_specs),
            "post_drain_shed_draining": len(post_sheds),
            "zero_lost_outputs": len(completed) == len(pre_specs),
            "tok_per_s": tok_per_s,
            **mfu_stamp(
                tok_per_s, engine.engine.config.model_config
            ),
            "wall_s": round(wall, 3),
        }
    finally:
        await engine.stop()


# ----------------------------------------------------- unified-arena gate


async def unified_gate() -> dict:
    """The perf_check ``unified`` section's measurement (ISSUE 14): a
    mixed RAG + adapter-churn workload whose combined working set is
    >= 4x the device pool, served through the full memory hierarchy —
    unified arena on HBM, host tier, disk tier.  A cold pass populates
    the tiers; the warm pass re-offers the SAME prefixes with fresh
    tails and must see warm-hit TTFT <= the gate's ratio of cold, with
    every request reaching a terminal outcome (zero allocation
    deadlocks) and the hierarchy demonstrably exercised (host
    evictions cascaded to disk, arena charges both directions)."""
    import shutil

    from tests.fixture_models import build_tiny_lora_adapter

    from vllm_tgis_adapter_tpu.engine.kv_cache import per_block_bytes

    # the gate runs the SMALL arch (see SMALL_ARCH note) so the
    # recompute-vs-promote ratio prices model compute, not host
    # fixed costs
    model_dir = build_small_llama(
        tempfile.mkdtemp(prefix="unified-gate-model-")
    )
    device_pool = 32
    prefix_len = 240  # tokens; 15 pages per distinct prefix — long
    #                   enough that recompute pays quadratic attention
    #                   while promotion pays linear page restores
    num_prefixes = 9  # 135 prefix pages = 4.2x the device pool
    working_set_pages = num_prefixes * (prefix_len // 16)
    ratio = working_set_pages * 16 / (device_pool * 16)

    pbb = per_block_bytes(_gate_config(model_dir, "none", device_pool))
    # host tier holds ~half the working set; the rest falls to disk
    host_gb = (working_set_pages // 2) * pbb / (1 << 30)
    disk_dir = tempfile.mkdtemp(prefix="unified-gate-disk-")

    # CPU-proxy fidelity (bench.py discipline)
    import jax

    jax.config.update("jax_cpu_enable_async_dispatch", False)

    adapters = {}
    engine = build_engine(
        model_dir,
        num_blocks=device_pool,
        max_seqs=4,
        prefill_buckets=(32, 64, 128, 256),
        supervised=False,
        kv_host_cache_gb=host_gb,
        kv_disk_cache_gb=1.0,
        kv_disk_cache_dir=disk_dir,
        max_loras=2,
        max_lora_rank=8,
    )
    # every warm request must actually PROMOTE: the default in-flight
    # promotion bound (8) would send the rest down the recompute path
    # and measure recompute-vs-recompute (the decode-role precedent —
    # core.set_replica_role widens the same bound)
    engine.engine.MAX_INFLIGHT_PROMOTIONS = 2 * num_prefixes
    try:
        for i, rank in enumerate((2, 4, 8, 2)):
            name = f"ad-uni-{i}"
            path = build_tiny_lora_adapter(
                os.path.join(model_dir, name), seed=20 + i, rank=rank,
                arch=SMALL_ARCH,
            )
            adapters[name] = (
                await engine.engine.lora_manager.load_lora_adapter(
                    name, path
                )
            )
        names = list(adapters)

        def specs_for(pass_tag: int) -> list[dict]:
            out = []
            for i in range(num_prefixes):
                prefix = [
                    3 + (31 * i + j) % 300 for j in range(prefix_len)
                ]
                tail = [
                    7 + (13 * (pass_tag * 100 + i) + j) % 300
                    for j in range(8)
                ]
                out.append({
                    "kind": "lora",
                    "lora_name": names[i % len(names)],
                    "prompt": prefix + tail,
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "seed": None,
                })
            return out

        async def run_pass(tag: str, pass_tag: int) -> dict:
            specs = specs_for(pass_tag)
            t0 = time.perf_counter()
            # full concurrency — the steady-state-under-load shape:
            # cold recomputes SERIALIZE on the device's prefill
            # compute, warm promotions ride the copy path off-loop
            # while resident work keeps the device busy
            tasks = [
                asyncio.create_task(run_timed_request(
                    engine, f"{tag}-{i}", spec,
                    adapters[spec["lora_name"]],
                ))
                for i, spec in enumerate(specs)
            ]
            done = await asyncio.wait_for(
                asyncio.gather(*tasks), SUITE_BOUND_S
            )
            ttfts = []
            toks = 0
            for status, result in done:
                if status != "ok":
                    raise RuntimeError(
                        f"unified gate {tag} request failed: {result!r}"
                    )
                toks += len(result["tokens"])
                if result["ttft_s"] is not None:
                    ttfts.append(result["ttft_s"])
            return {
                "ttft_p50": _pct(ttfts, 0.50),
                "tokens": toks,
                "completed": len(done),
                "wall_s": time.perf_counter() - t0,
            }

        # compile warm-up on throwaway prefixes (never timed — the r05
        # lesson), then the measured cold pass on FRESH prefixes
        await run_pass("compile", 9)
        cold = await run_pass("cold", 0)
        # warm: the identical prompts re-sent (the kv_tier gate's
        # warm-hit definition — match_prefix caps one token short, so
        # promotion covers everything but the final position and the
        # tiers, not recompute, serve the pass)
        warm = await run_pass("warm", 0)

        core = engine.engine
        tier = core.kv_tier.debug_state()
        arena = core.arena.debug_state() if core.arena else None
        pool = core.runner.adapter_pool
        tok_per_s = round(
            (cold["tokens"] + warm["tokens"])
            / max(cold["wall_s"] + warm["wall_s"], 1e-9),
            1,
        )
        line = {
            "kind": "unified",
            "device_pool_pages": device_pool,
            "working_set_pages": working_set_pages,
            "working_set_ratio": round(ratio, 2),
            "ttft_ms_p50_cold": _round_ms(cold["ttft_p50"]),
            "ttft_ms_p50_warm": _round_ms(warm["ttft_p50"]),
            "warm_cold_ratio": round(
                warm["ttft_p50"] / max(cold["ttft_p50"], 1e-9), 4
            ),
            "completed": cold["completed"] + warm["completed"],
            "offered": 2 * num_prefixes,
            "tier": {
                "host": {
                    k: tier[k]
                    for k in ("demoted_pages", "promoted_pages",
                              "evictions", "dropped_corrupt")
                },
                "disk": tier["tiers"]["disk"],
            },
            "arena": arena,
            "adapter_churn": {
                "swaps_in": pool.swaps_in,
                "swaps_out": pool.swaps_out,
                "resident_high_water": pool.resident_high_water,
            },
            **mfu_stamp(tok_per_s, core.config.model_config),
        }
        return line
    finally:
        await engine.stop()
        shutil.rmtree(disk_dir, ignore_errors=True)


async def cross_host_gate(model_dir: str) -> dict:
    """perf_check ``cross_host`` section (docs/CROSS_HOST.md): the
    remote-vs-local handoff cost, measured honestly.

    The SAME prefill→decode request runs twice — once on a dp=2
    prefill+decode fleet whose handoff crosses the in-process shared
    tier (the PR 11 path), once on a prefill-only host whose handoff
    crosses a real loopback TCP kvnet to a peered decode host.  Both
    sides warm their compile sets with a disjoint same-shape prompt
    first, so the measured pass prices serialization + wire + remote
    resume, not XLA tracing.  A third leg re-sends the measured prompt
    on the DECODE host, whose prefix pages now live only on the
    prefill host — stamping the remote-prefix-fetch TTFT and hit
    count."""
    import socket

    from vllm_tgis_adapter_tpu import metrics

    measured_prompt = [5 + (i % 40) for i in range(48)]  # 3 pages
    warm_prompt = [211 + (i % 29) for i in range(48)]    # same shape
    spec = {"kind": "chat", "prompt": measured_prompt,
            "temperature": 0.0, "seed": None, "max_tokens": 32,
            "logprobs": None}
    warm_spec = {**spec, "prompt": warm_prompt}

    def _port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _fleet(**kw):  # noqa: ANN003, ANN202
        # prefix registration demotes prompt pages at prefill commit —
        # the networked tier's INDEX visibility without LRU pressure
        return build_engine(
            model_dir, kv_host_cache_gb=1.0,
            enable_prefix_caching=False, **kw,
        )

    # ---- local handoff: dp=2 prefill+decode, shared in-process tier
    local = _fleet(dp=2, roles=("prefill", "decode"))
    await local.start()
    status, _ = await run_timed_request(local, "xh-warm-l", warm_spec,
                                        None)
    assert status == "ok", "local warm failed"
    status, local_m = await run_timed_request(local, "xh-meas-l", spec,
                                              None)
    assert status == "ok", f"local measured failed: {local_m!r}"
    local_handoffs = dict(local.handoff_outcomes)
    await local.stop()

    # ---- remote handoff: prefill-only A → kvnet → mixed B
    port_a, port_b = _port(), _port()
    a = _fleet(roles=("prefill",),
               kvnet_listen=f"127.0.0.1:{port_a}",
               kvnet_peers=(f"127.0.0.1:{port_b}",), kvnet_node_id="A")
    b = _fleet(kvnet_listen=f"127.0.0.1:{port_b}",
               kvnet_peers=(f"127.0.0.1:{port_a}",), kvnet_node_id="B")
    try:
        await a.start()
        await b.start()
        for _ in range(100):
            if a.kvnet.peers[0].connected:
                break
            await asyncio.sleep(0.05)
        remote_before = (
            metrics.kvnet_handoffs_total.labels(outcome="remote")
            ._value.get()  # noqa: SLF001
        )
        status, _ = await run_timed_request(a, "xh-warm-r", warm_spec,
                                            None)
        assert status == "ok", "remote warm failed"
        status, remote_m = await run_timed_request(a, "xh-meas-r", spec,
                                                   None)
        assert status == "ok", f"remote measured failed: {remote_m!r}"
        remote_handoffs = (
            metrics.kvnet_handoffs_total.labels(outcome="remote")
            ._value.get()  # noqa: SLF001
            - remote_before
        )

        # ---- remote prefix fetch: a THIRD prompt served first on B
        # (B is mixed — no handoff, so its pages live only in B's
        # tier), then requested on A, whose prefill must pull the
        # prefix over the wire instead of recomputing it.  Measured on
        # A's TTFT — the fetch sits on the time-to-first-token path.
        from vllm_tgis_adapter_tpu.engine.kv_cache import chain_digests

        prefix_prompt = [97 + (i % 31) for i in range(48)]
        prefix_spec = {**spec, "prompt": prefix_prompt}
        status, prefix_base = await run_timed_request(
            b, "xh-prefix-warm", prefix_spec, None
        )
        assert status == "ok", "remote-prefix warm on B failed"
        wanted = set(chain_digests(prefix_prompt, 16, None, 3))
        for _ in range(100):
            if wanted <= set(a.kvnet.peers[0].mirror):
                break
            await asyncio.sleep(0.05)
        hits_before = (
            metrics.kvnet_remote_hits_total._value.get()  # noqa: SLF001
        )
        status, prefix_m = await run_timed_request(
            a, "xh-prefix", prefix_spec, None
        )
        assert status == "ok", f"remote-prefix leg failed: {prefix_m!r}"
        prefix_hits = (
            metrics.kvnet_remote_hits_total._value.get()  # noqa: SLF001
            - hits_before
        )
    finally:
        await a.stop()
        await b.stop()

    return {
        "kind": "cross_host",
        "local": {
            "wall_s": round(local_m["wall_s"], 4),
            "ttft_ms": _round_ms(local_m["ttft_s"]),
            "handoffs_completed": local_handoffs["completed"],
        },
        "remote": {
            "wall_s": round(remote_m["wall_s"], 4),
            "ttft_ms": _round_ms(remote_m["ttft_s"]),
            "handoffs_remote": int(remote_handoffs),
        },
        "overhead_ratio": round(
            remote_m["wall_s"] / max(local_m["wall_s"], 1e-9), 3
        ),
        "token_identical": remote_m["tokens"] == local_m["tokens"]
        and prefix_m["tokens"] == prefix_base["tokens"],
        "remote_prefix": {
            "hits": int(prefix_hits),
            "ttft_ms": _round_ms(prefix_m["ttft_s"]),
        },
    }


async def steady_state(model_dir: str, adapter_dir: str) -> dict:
    """Plain steady-state run of every suite on the default engine —
    the non-gating inspection entry point."""
    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=16,
        prefill_buckets=(32, 64, 128), supervised=False,
    )
    try:
        lora_req = await engine.engine.lora_manager.load_lora_adapter(
            "ad-soak", adapter_dir
        )
        suites = {}
        for suite in SUITES:
            specs = make_suite_workload(suite, random.Random(0))
            await run_suite(engine, specs, lora_req, f"warm-{suite}")
            line = await run_suite(engine, specs, lora_req, suite)
            line.pop("requests")
            suites[suite] = line
    finally:
        await engine.stop()
    # the bursty and drain suites boot their own engines (bounded
    # queue / drain coordination do not compose with a shared one)
    suites["bursty_multitenant"] = await bursty_multitenant(
        model_dir, adapter_dir
    )
    suites["drain_under_load"] = await drain_under_load(
        model_dir, adapter_dir
    )
    return {"kind": "scenarios", "suites": suites}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quant-gate", action="store_true",
                        help="run the bf16-vs-quantized comparison and "
                             "print one JSON line (perf_check `quant`)")
    parser.add_argument("--unified-gate", action="store_true",
                        help="run the unified-arena tiered-memory "
                             "measurement (working set 4x HBM, warm vs "
                             "cold TTFT) and print one JSON line "
                             "(perf_check `unified`)")
    parser.add_argument("--cross-host-gate", action="store_true",
                        help="measure remote-vs-local handoff cost over "
                             "a loopback kvnet fleet and print one JSON "
                             "line (perf_check `cross_host` — "
                             "docs/CROSS_HOST.md)")
    parser.add_argument("--suite", default=None,
                        choices=["bursty_multitenant",
                                 "drain_under_load"],
                        help="run ONE special suite and print its line")
    parser.add_argument("--scheme", default="int8",
                        choices=["int8", "fp8"],
                        help="--kv-quantization scheme under test")
    args = parser.parse_args(argv)

    model_dir, adapter_dir = build_fixtures()
    if args.quant_gate:
        line = asyncio.run(quant_gate(model_dir, adapter_dir, args.scheme))
    elif args.cross_host_gate:
        line = asyncio.run(cross_host_gate(model_dir))
    elif args.unified_gate:
        line = asyncio.run(unified_gate())
    elif args.suite == "bursty_multitenant":
        line = asyncio.run(bursty_multitenant(model_dir, adapter_dir))
    elif args.suite == "drain_under_load":
        line = asyncio.run(drain_under_load(model_dir, adapter_dir))
    else:
        line = asyncio.run(steady_state(model_dir, adapter_dir))
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
