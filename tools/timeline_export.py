"""Offline chrome-trace export from debug-state dumps.

A stall watchdog dump, a saved ``GET /debug/state`` response, or a
``DumpState`` RPC payload all carry the same snapshot — step-anatomy
records, flight-recorder events, doctor episodes.  This tool turns one
of them into a Perfetto-loadable chrome-trace JSON *after the fact*,
when the serving process may be long gone:

    python tools/timeline_export.py stall_dump.json -o timeline.json
    python tools/timeline_export.py state.json --ledger-log ledger.jsonl

``--ledger-log`` folds a ``--ledger-log`` JSONL file in as offline
per-request spans (arrival → last decode), so request lifetimes line
up under the step tracks they were served by.  The exporter is the
exact same code path as ``GET /debug/timeline`` and the ``GetTimeline``
RPC (telemetry/timeline.py) — one serializer, three surfaces.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def load_ledger_records(path: str) -> list[dict]:
    """--ledger-log JSONL → record dicts (bad lines are skipped loudly:
    a torn final line from a killed process must not void the export)."""
    records: list[dict] = []
    skipped = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                skipped += 1
    if skipped:
        print(
            f"warning: skipped {skipped} unparsable ledger line(s)",
            file=sys.stderr,
        )
    return records


def main(argv: "list[str] | None" = None) -> int:
    parser = argparse.ArgumentParser(
        description="debug-state dump -> Perfetto chrome-trace JSON",
    )
    parser.add_argument(
        "state",
        help="debug-state JSON file (stall dump, saved /debug/state "
        "response, or DumpState payload)",
    )
    parser.add_argument(
        "-o", "--output",
        help="output path (default: <state stem>.trace.json)",
    )
    parser.add_argument(
        "--ledger-log",
        help="--ledger-log JSONL to fold in as offline request spans",
    )
    parser.add_argument(
        "--last-steps", type=int, default=None,
        help="cap on StepRecords per replica (default: all in the dump)",
    )
    parser.add_argument(
        "--format", default="chrome", choices=("chrome",),
        help="export format (chrome-trace JSON is the only format)",
    )
    args = parser.parse_args(argv)

    from vllm_tgis_adapter_tpu.telemetry.timeline import (
        chrome_trace_from_state,
    )

    try:
        with open(args.state, encoding="utf-8") as fh:
            state = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read {args.state}: {e}", file=sys.stderr)
        return 2
    if not isinstance(state, dict):
        print(
            f"error: {args.state} is not a debug-state object",
            file=sys.stderr,
        )
        return 2

    ledger_records = (
        load_ledger_records(args.ledger_log) if args.ledger_log else None
    )
    trace = chrome_trace_from_state(
        state, ledger_records=ledger_records, last_steps=args.last_steps
    )
    out = args.output or str(
        Path(args.state).with_suffix("").name + ".trace.json"
    )
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(trace, fh, default=str)
    n_events = len(trace["traceEvents"])
    print(
        f"wrote {out}: {n_events} trace events from "
        f"{len(state.get('step_timeline', {}).get('replicas', []))} "
        f"replica(s) — open in https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
