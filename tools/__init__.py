"""Repo tooling (CI gates, profiling drivers, static analysis)."""
