"""The reviewed lifecycle grammar: ``LIFECYCLE_MANIFEST``.

One declarative spec of the two lifecycle machines the control plane
must respect, checked in and diffed under review exactly like the
compile-lattice manifest (tools/tpulint/lattice_manifest.json):

* **per-request flight-recorder event DFA** — which
  ``FlightRecorder.record(kind, request_id=...)`` event may follow
  which, per request, per recorder.  The teeth are at the boundaries:
  a request's stream must OPEN with a declared entry kind (``admit``
  on the serving replica; ``resume``/``handoff_in`` on a replica
  adopting recovered work; ``shed`` for requests refused before
  admission; ``ledger`` on replica 0's recorder for requests served
  elsewhere — the fleet-level ledger closes there regardless of where
  the request ran), and once ``ledger`` closes the stream NOTHING may
  follow (a double ledger close is exactly the shed-vs-stream race the
  ledger had to special-case).  Between those boundaries the active
  kinds may interleave freely — preemption, swaps, tier demote/promote,
  checkpoints and handoffs genuinely reorder under load, and
  over-constraining the middle would turn real schedules into false
  positives.
* **engine lifecycle machine** — the legal
  ``serving``/``recovering``/``draining``/``dead`` transitions
  (supervisor/lifecycle.py states), including the one schedule-
  dependent rule the supervisor's recovery tail exists to uphold:
  **never ``recovering`` → ``serving`` while the front door is
  draining** (a SIGTERM that lands mid-recovery must win).

Enforced three ways (docs/STATIC_ANALYSIS.md "Lifecycle grammar"):
statically by tpulint TPL511/TPL512 (every ``record(...)`` call site
and lifecycle-transition site must use a declared kind/state/edge — a
new event kind becomes a reviewed diff of THIS file), at runtime by the
``TGIS_TPU_SANITIZE=1`` sanitizer (event ORDER per request, lifecycle
edges as they happen), and by the dettest explorer on every explored
schedule.  tools/obs_check.py cross-checks the kind list here against
``flight_recorder.EVENT_KINDS`` and docs/OBSERVABILITY.md so the three
sources cannot drift.
"""

from __future__ import annotations

# Kinds that appear mid-stream for a live request, in any order: the
# engine genuinely interleaves these under preemption/recovery load.
_ACTIVE = (
    "prefill",
    "packed_prefill",
    "ragged_step",
    "decode_progress",
    "preempt",
    "swap_out",
    "swap_in",
    "demote_host",
    "promote_host",
    "checkpoint",
    "resume",
    "handoff_out",
    "handoff_in",
    "remote_hit",
    "remote_handoff_in",
)

# Terminal *outcome* kinds: after one of these only outcome-adjacent
# events and the ledger close may follow.  finish→demote_host covers
# finish-time prefix registration into the host tier; abort/finish may
# land in either order when a client abort races the final frame
# (docs/RECOVERY.md "abort while checkpointed"); a shed noted by the
# front door is followed by the stream-level exit of the same request.
_AFTER_FINISH = ("ledger", "demote_host", "handoff_out", "abort")
_AFTER_ABORT = ("ledger", "finish", "demote_host", "checkpoint")
_AFTER_SHED = ("ledger", "abort", "finish")

_OPEN = _ACTIVE + ("finish", "abort", "shed", "ledger")

LIFECYCLE_MANIFEST = {
    "version": 1,
    "request_events": {
        # first event a recorder may see for a request id
        "entry": [
            "admit", "resume", "handoff_in", "remote_handoff_in",
            "shed", "ledger",
        ],
        # kinds after which the stream is closed (empty successor set)
        "terminal": ["ledger"],
        "edges": {
            "admit": list(_OPEN),
            **{kind: list(_OPEN) for kind in _ACTIVE},
            "finish": list(_AFTER_FINISH),
            "abort": list(_AFTER_ABORT),
            "shed": list(_AFTER_SHED),
            "ledger": [],
        },
    },
    "engine_lifecycle": {
        "states": ["serving", "recovering", "draining", "dead"],
        "entry": ["serving"],
        "edges": [
            ["serving", "serving"],
            ["serving", "recovering"],
            ["serving", "draining"],
            ["serving", "dead"],
            ["recovering", "recovering"],
            ["recovering", "serving"],
            ["recovering", "draining"],
            ["recovering", "dead"],
            ["draining", "draining"],
            ["draining", "recovering"],
            ["draining", "dead"],
        ],
        # edges additionally forbidden while the front door is draining
        # — legal in general, illegal under SIGTERM (the ISSUE 17
        # invariant: recovery must not flip a draining pod back to
        # serving)
        "forbidden_while_draining": [["recovering", "serving"]],
    },
    # batch-level kinds: recorded WITHOUT a request_id (whole-wave /
    # whole-engine events), so they are outside the per-request DFA.
    # Declared here so tpulint TPL511 can reject a record() call whose
    # kind is in NO part of the manifest, and so obs_check can assert
    # request ∪ batch == flight_recorder.EVENT_KINDS exactly.
    "batch_events": [
        "decode", "error", "restart", "stall", "doctor",
        # kvnet (docs/CROSS_HOST.md): whole-host peer traffic, outside
        # any one request's DFA
        "remote_put", "peer_up", "peer_down",
    ],
}


# --------------------------------------------------------------- accessors


def request_edges() -> dict[str, frozenset[str]]:
    ev = LIFECYCLE_MANIFEST["request_events"]
    return {k: frozenset(v) for k, v in ev["edges"].items()}


def request_entry_kinds() -> frozenset[str]:
    return frozenset(LIFECYCLE_MANIFEST["request_events"]["entry"])


def request_kinds() -> frozenset[str]:
    """Every kind declared trackable per request."""
    return frozenset(LIFECYCLE_MANIFEST["request_events"]["edges"])


def batch_kinds() -> frozenset[str]:
    """Kinds recorded without a request_id (outside the per-request DFA)."""
    return frozenset(LIFECYCLE_MANIFEST["batch_events"])


def all_kinds() -> frozenset[str]:
    """Every declared kind — must equal ``flight_recorder.EVENT_KINDS``."""
    return request_kinds() | batch_kinds()


def engine_states() -> frozenset[str]:
    return frozenset(LIFECYCLE_MANIFEST["engine_lifecycle"]["states"])


def engine_edges() -> frozenset[tuple[str, str]]:
    return frozenset(
        (a, b) for a, b in LIFECYCLE_MANIFEST["engine_lifecycle"]["edges"]
    )


def engine_entry_states() -> frozenset[str]:
    return frozenset(LIFECYCLE_MANIFEST["engine_lifecycle"]["entry"])


def forbidden_while_draining() -> frozenset[tuple[str, str]]:
    return frozenset(
        (a, b)
        for a, b in LIFECYCLE_MANIFEST["engine_lifecycle"][
            "forbidden_while_draining"
        ]
    )


# --------------------------------------------------------------- validation


def self_check() -> list[str]:
    """Internal-consistency problems of the manifest itself (empty =
    sound).  ``nox -s race_check`` runs this before any exploration."""
    problems: list[str] = []
    edges = request_edges()
    kinds = request_kinds()
    for kind in request_entry_kinds():
        if kind not in kinds:
            problems.append(f"entry kind {kind!r} has no edge declaration")
    for kind, successors in edges.items():
        undeclared = successors - kinds
        if undeclared:
            problems.append(
                f"{kind!r} declares undeclared successor(s) "
                f"{sorted(undeclared)}"
            )
    for kind in LIFECYCLE_MANIFEST["request_events"]["terminal"]:
        if edges.get(kind):
            problems.append(
                f"terminal kind {kind!r} declares successors "
                f"{sorted(edges[kind])}"
            )
    overlap = request_kinds() & batch_kinds()
    if overlap:
        problems.append(
            f"kind(s) declared both per-request and batch-level: "
            f"{sorted(overlap)}"
        )
    states = engine_states()
    for a, b in engine_edges() | forbidden_while_draining():
        for s in (a, b):
            if s not in states:
                problems.append(f"lifecycle edge state {s!r} undeclared")
    for s in engine_entry_states():
        if s not in states:
            problems.append(f"lifecycle entry state {s!r} undeclared")
    if ("dead", "serving") in engine_edges():
        problems.append("dead must be terminal (dead->serving declared)")
    return problems


def verify_request_stream(
    kinds: "list[str]", request_id: str = "?"
) -> None:
    """Replay one request's recorded kind sequence through the DFA;
    raises ``ValueError`` naming the violated edge.  The explorer runs
    this over every recorder of every explored schedule."""
    edges = request_edges()
    entry = request_entry_kinds()
    prev: "str | None" = None
    for kind in kinds:
        if prev is None:
            ok = kind in entry
        else:
            ok = kind in edges.get(prev, frozenset())
        if not ok:
            raise ValueError(
                f"request {request_id!r}: event {kind!r} after "
                f"{prev if prev is not None else 'stream start'!r} is not "
                f"a declared lifecycle edge"
            )
        prev = kind
