"""The concurrency-critical control-plane scenarios dettest explores.

Each :class:`Scenario` drives REAL control-plane objects (the front
door, the engine supervisor, the host KV tier, the adapter pool, the
cost ledger) on a :class:`~tools.dettest.loop.DetLoop`, with only the
device/engine layers stubbed — the races under test live entirely in
the host-side state machines, so the stubs preserve every await point
the real code has (``to_thread`` sections become chooser-visible
schedule points on the deterministic loop).

Invariants checked on EVERY explored schedule (``check``):

* exactly one ledger record per request (``CostLedger`` open/close
  conservation, one ``ledger`` flight-recorder event each);
* no leaked admission slot (``FrontDoor._pending_grants`` and the
  scenario's slot accounting both return to zero);
* no lost output (every request reaches exactly one terminal outcome);
* lifecycle never goes ``recovering → serving`` while draining;
* tier/pool resource conservation (KV in-flight bytes return to zero,
  adapter slots are a permutation of the pool).

The explorer additionally replays every recorder's per-request event
stream through the lifecycle grammar
(:mod:`tools.dettest.lifecycle_grammar`).

:data:`FAILPOINT` is an INTENTIONALLY racy scenario (the historical
grant-cancellation slot over-grant, reconstructed as a check-then-act
window): ``race_check`` uses it to prove the harness finds seeded
races and reproduces a recorded failing seed byte-for-byte.
"""

from __future__ import annotations

import asyncio
import os
from types import SimpleNamespace

import numpy as np

from vllm_tgis_adapter_tpu.engine.adapter_pool import AdapterPool
from vllm_tgis_adapter_tpu.engine.config import FrontdoorConfig
from vllm_tgis_adapter_tpu.engine.kv_tier import HostKVTier, PromotionTicket
from vllm_tgis_adapter_tpu.flight_recorder import FlightRecorder
from vllm_tgis_adapter_tpu.frontdoor.admission import FrontDoor
from vllm_tgis_adapter_tpu.frontdoor.errors import AdmissionShedError
from vllm_tgis_adapter_tpu.supervisor.lifecycle import LIFECYCLE_SERVING
from vllm_tgis_adapter_tpu.supervisor.supervisor import EngineSupervisor
from vllm_tgis_adapter_tpu.telemetry.doctor import Doctor, ReplicaSignals
from vllm_tgis_adapter_tpu.telemetry.ledger import CostLedger
from vllm_tgis_adapter_tpu.utils import spawn_task

__all__ = ["FAILPOINT", "SCENARIOS", "Scenario"]


class Scenario:
    """One explorable control-plane scenario.

    ``build`` returns a fresh state object (new loop-bound primitives
    every run — nothing may leak between schedules); ``run`` is the
    coroutine the DetLoop executes; ``check`` raises on any violated
    invariant; ``recorders`` exposes the flight recorders whose
    per-request streams the explorer grammar-verifies.
    """

    name = "?"

    def build(self):  # noqa: ANN201
        raise NotImplementedError

    async def run(self, state) -> None:  # noqa: ANN001
        raise NotImplementedError

    def check(self, state) -> None:  # noqa: ANN001
        raise NotImplementedError

    def recorders(self, state) -> list:  # noqa: ANN001
        return []


def _gather(tasks):  # noqa: ANN001, ANN202
    return asyncio.gather(*tasks, return_exceptions=True)


# ----------------------------------------------------------- 1. front door


class FrontDoorScenario(Scenario):
    """Admission grant vs client cancellation vs queue TTL vs drain.

    A two-slot engine behind a real :class:`FrontDoor`: greedy clients
    race for slots, a canceller tears two of them down mid-wait, two
    park with short TTLs, and a SIGTERM drain lands in the middle of
    it all.  Every request must end with exactly one ledger record and
    the admission window must conserve slots on every interleaving —
    this is the scenario that would have caught the historical
    grant-cancellation slot leak.
    """

    name = "frontdoor-admit-cancel-ttl-drain"
    SLOTS = 2

    def build(self):  # noqa: ANN201
        state = SimpleNamespace(
            recorder=FlightRecorder(),
            active=0,
            outcomes={},
            tasks=set(),
        )
        state.ledger = CostLedger(recorder=state.recorder.record)
        config = FrontdoorConfig(
            enabled=True,
            max_waiting_requests=8,
            admission_deadline_s=0.0,
            queue_ttl_s=0.0,
            drain_grace_s=1.0,
        )
        state.fd = FrontDoor(
            config,
            admit_window=self.SLOTS,
            room_fn=lambda pending: state.active + pending < self.SLOTS,
            waiting_depth_fn=lambda: 0,
            backlog_tokens_fn=lambda: 0.0,
            kv_token_capacity_fn=lambda: 4096.0,
            record_shed=lambda rid, tenant, reason, **d: (
                state.recorder.record("shed", rid, tenant=tenant,
                                      reason=reason)
            ),
        )
        return state

    async def _client(self, state, rid, tenant, *, deadline=None,  # noqa: ANN001, ANN002
                      hold_s=0.02) -> None:
        import time

        fd, ledger = state.fd, state.ledger
        ledger.open(rid, tenant=tenant, tokens_in=8)
        try:
            await fd.acquire(
                request_id=rid, tenant=tenant, tokens=8.0,
                deadline=(time.time() + deadline)
                if deadline is not None else None,
            )
        except AdmissionShedError as exc:
            ledger.note_shed(rid, exc.reason)
            ledger.close(rid, "shed")
            state.outcomes[rid] = "shed"
            return
        except asyncio.CancelledError:
            ledger.close(rid, "abort")
            state.outcomes[rid] = "cancelled"
            raise
        # granted: hand the slot to the "engine" and serve
        fd.note_admitted()
        state.active += 1
        state.recorder.record("admit", rid, tenant=tenant)
        try:
            await asyncio.sleep(hold_s)
            state.recorder.record("finish", rid)
            ledger.close(rid, "finish")
            state.outcomes[rid] = "finish"
        except asyncio.CancelledError:
            state.recorder.record("abort", rid)
            ledger.close(rid, "abort")
            state.outcomes[rid] = "cancelled"
            raise
        finally:
            state.active -= 1
            fd.kick()

    async def run(self, state) -> None:  # noqa: ANN001
        clients = {}
        for i, (rid, tenant, deadline) in enumerate([
            ("fd-r0", "a", None),
            ("fd-r1", "a", None),
            ("fd-r2", "b", None),
            ("fd-r3", "b", None),
            ("fd-r4", "a", 0.01),  # short TTL: sheds if parked too long
            ("fd-r5", "b", 0.01),
        ]):
            clients[rid] = spawn_task(
                self._client(state, rid, tenant, deadline=deadline),
                name=f"client-{rid}", retain=state.tasks,
            )

        async def _cancel(rid: str, after: float) -> None:
            await asyncio.sleep(after)
            clients[rid].cancel()

        async def _drain(after: float) -> None:
            await asyncio.sleep(after)
            state.fd.begin_drain()

        side = [
            spawn_task(_cancel("fd-r2", 0.005), name="canceller-r2",
                       retain=state.tasks),
            spawn_task(_cancel("fd-r3", 0.005), name="canceller-r3",
                       retain=state.tasks),
            spawn_task(_drain(0.03), name="sigterm-drain",
                       retain=state.tasks),
        ]
        await _gather(list(clients.values()) + side)
        await state.fd.shutdown()

    def check(self, state) -> None:  # noqa: ANN001
        fd, ledger = state.fd, state.ledger
        assert state.active == 0, f"engine slots leaked: {state.active}"
        assert fd._pending_grants == 0, (  # noqa: SLF001
            f"admission slots leaked: {fd._pending_grants} grants "  # noqa: SLF001
            "outstanding after every client finished"
        )
        assert fd.parked == 0, f"{fd.parked} requests left parked"
        assert ledger.open_count == 0, (
            f"{ledger.open_count} ledger records never closed"
        )
        assert ledger.closed_total == 6, (
            f"expected 6 ledger closes, got {ledger.closed_total}"
        )
        assert len(state.outcomes) == 6, (
            f"lost output: only {sorted(state.outcomes)} reached a "
            "terminal outcome"
        )
        per_request = {}
        for event in state.recorder.events():
            if event["kind"] == "ledger":
                rid = event["request_id"]
                per_request[rid] = per_request.get(rid, 0) + 1
        assert all(n == 1 for n in per_request.values()), (
            f"duplicate ledger close events: {per_request}"
        )
        assert len(per_request) == 6, (
            f"missing ledger events: {sorted(per_request)}"
        )

    def recorders(self, state) -> list:  # noqa: ANN001
        return [state.recorder]


# ----------------------------------------------------------- 2. supervisor


class _SubEngine:
    """Per-replica engine stub: just the surface _recover_one touches."""

    def __init__(self) -> None:
        self.recorder = FlightRecorder()
        self.step_counter = 0
        self.replica_index = 0
        self.role = "mixed"

    def set_replica_role(self, role: str) -> None:
        self.role = role


class _StubReplica:
    def __init__(self, index: int) -> None:
        self.index = index
        self.engine = _SubEngine()
        self.serving = True
        self.task = None
        self.role = "mixed"


class _FleetEngine:
    """Fleet-level engine stub implementing the supervisor's recovery
    contract, with an await point per phase so quiesce → triage →
    rebuild is fully reorderable against racing deaths and SIGTERM."""

    def __init__(self) -> None:
        self.lifecycle = LIFECYCLE_SERVING
        self.frontdoor = None
        self._replicas = []
        self._precompile_widths = None
        self.dead_event = asyncio.Event()
        self.terminal = None

    async def fail_unreplayable(self, rep, err):  # noqa: ANN001, ANN201
        await asyncio.sleep(0)
        return 0, [f"ckpt-{rep.index}"]

    def staged_checkpoints(self, checkpoints):  # noqa: ANN001, ANN201
        return checkpoints

    async def replay_to_replicas(self, rep):  # noqa: ANN001, ANN201
        await asyncio.sleep(0)
        healthy = [r for r in self._replicas if r.serving]
        return 1 if healthy else 0

    async def resume_to_replicas(self, rep, checkpoints, err):  # noqa: ANN001, ANN201
        await asyncio.sleep(0)
        healthy = [r for r in self._replicas if r.serving]
        if healthy and checkpoints:
            return len(checkpoints), 0, []
        return 0, 0, checkpoints

    async def restart_replica(self, rep, new_engine, err):  # noqa: ANN001, ANN201
        await asyncio.sleep(0)
        rep.engine = new_engine
        return 1, 0

    async def resume_into(self, rep, checkpoints, err):  # noqa: ANN001, ANN201
        await asyncio.sleep(0)
        return len(checkpoints), 0

    def _arm_replica(self, rep) -> None:  # noqa: ANN001
        pass

    def _terminal_death(self, final) -> None:  # noqa: ANN001
        self.terminal = final


class _DetSupervisor(EngineSupervisor):
    """Real supervisor with the (slow, device-touching) rebuild stubbed;
    the rebuild still runs through ``to_thread`` so it stays a genuine
    schedule point."""

    def _rebuild(self, old):  # noqa: ANN001, ANN201
        return _SubEngine()


class SupervisorScenario(Scenario):
    """Quiesce → triage → rebuild racing SIGTERM and a second replica
    death.

    Replica 0 and replica 1 die at the SAME virtual instant a SIGTERM
    drain lands: depending on the schedule the second death arrives
    before, during, or after the first recovery, and the drain lands
    anywhere inside the recovery pipeline.  On every interleaving both
    replicas must come back armed, the pending-death queue must empty,
    and the lifecycle must never flip ``recovering → serving`` while
    the front door is draining (the runtime sanitizer enforces the
    same edge; the scenario also checks it explicitly from the
    listener's view).
    """

    name = "supervisor-recovery-vs-sigterm"

    def build(self):  # noqa: ANN201
        state = SimpleNamespace(transitions=[], tasks=set())
        fleet = _FleetEngine()
        fleet._replicas = [_StubReplica(0), _StubReplica(1)]  # noqa: SLF001
        config = FrontdoorConfig(enabled=True, drain_grace_s=1.0)
        fleet.frontdoor = FrontDoor(
            config,
            admit_window=2,
            room_fn=lambda pending: True,
            waiting_depth_fn=lambda: 0,
            backlog_tokens_fn=lambda: 0.0,
            kv_token_capacity_fn=lambda: 4096.0,
        )
        state.fleet = fleet
        state.sup = _DetSupervisor(
            fleet, max_restarts=4, window_s=10.0, backoff_base_s=0.0,
            termination_log=os.devnull,
        )

        def _listener(new_state: str) -> None:
            state.transitions.append(
                (new_state, fleet.frontdoor.draining)
            )

        state.sup.add_listener(_listener)
        return state

    async def run(self, state) -> None:  # noqa: ANN001
        sup, fleet = state.sup, state.fleet

        async def _die(rep) -> None:  # noqa: ANN001
            await asyncio.sleep(0.01)
            sup.notify_death(rep, RuntimeError(f"boom-{rep.index}"))

        async def _sigterm() -> None:
            await asyncio.sleep(0.01)
            fleet.frontdoor.begin_drain()

        await _gather([
            spawn_task(_die(fleet._replicas[0]), name="death-rep0",  # noqa: SLF001
                       retain=state.tasks),
            spawn_task(_die(fleet._replicas[1]), name="death-rep1",  # noqa: SLF001
                       retain=state.tasks),
            spawn_task(_sigterm(), name="sigterm", retain=state.tasks),
        ])
        # wait out the recovery task (and any re-queued deaths); an
        # escalation to dead ends the scenario too — check() rejects it
        while fleet.lifecycle != "dead" and (
            sup._pending  # noqa: SLF001
            or (sup._task is not None and not sup._task.done())  # noqa: SLF001
        ):
            await asyncio.sleep(0.01)

    def check(self, state) -> None:  # noqa: ANN001
        sup, fleet = state.sup, state.fleet
        assert not sup._pending, (  # noqa: SLF001
            f"deaths stranded in the pending queue: {sup._pending}"  # noqa: SLF001
        )
        assert fleet.lifecycle != "recovering", (
            "recovery finished but lifecycle is still 'recovering'"
        )
        assert fleet.terminal is None, (
            f"supervisor escalated unexpectedly: {fleet.terminal}"
        )
        for rep in fleet._replicas:  # noqa: SLF001
            assert rep.serving, (
                f"replica {rep.index} never re-armed after recovery"
            )
        recovered = [
            h for h in sup.restart_history if h.get("recovered")
        ]
        assert len(recovered) == len(sup.restart_history) == 2, (
            f"expected 2 recovered attempts, got {sup.restart_history}"
        )
        # the ISSUE invariant, from the listener's own view: recovery
        # must never flip a draining pod back to serving
        last = None
        for new_state, draining in state.transitions:
            assert not (
                last == "recovering" and new_state == "serving" and draining
            ), (
                "lifecycle went recovering -> serving while the front "
                f"door was draining (transitions: {state.transitions})"
            )
            last = new_state
        # SIGTERM always lands in this scenario: whoever transitioned
        # last must have respected it
        assert fleet.frontdoor.draining
        assert fleet.lifecycle in ("serving", "draining")

    def recorders(self, state) -> list:  # noqa: ANN001
        return [rep.engine.recorder for rep in state.fleet._replicas]  # noqa: SLF001


# -------------------------------------------------------------- 3. kv tier


class KvTierScenario(Scenario):
    """PromotionTicket staging vs abort vs eviction pressure.

    Demotions stream into a byte-budgeted tier while two promotion
    tickets assemble against it; one ticket is cancelled mid-flight
    and a burst of fresh demotions evicts entries under the other's
    assembly.  On every interleaving the tier's byte accounting must
    balance, in-flight markers must drain, and every ticket must reach
    ``ready`` exactly once with a page span consistent with its
    bounds.
    """

    name = "kvtier-promotion-vs-abort-preempt"
    BLOCK = 4

    def build(self):  # noqa: ANN201
        page = np.zeros((2, 8), np.float32)  # 64 bytes/array
        state = SimpleNamespace(
            # budget holds ~4 pages of 2x64B: eviction pressure is real
            tier=HostKVTier(budget_bytes=560, block_size=self.BLOCK),
            page=page,
            tickets=[],
        )
        return state

    @staticmethod
    def _batch(state, digests):  # noqa: ANN001, ANN202
        return [
            (d, state.page.copy(), state.page.copy()) for d in digests
        ]

    async def run(self, state) -> None:  # noqa: ANN001
        tier = state.tier
        digests = [b"pg-%d" % i for i in range(8)]
        tier.submit(self._batch(state, digests[:4]))

        t_warm = PromotionTicket(
            request_id="kv-warm", digests=digests[:3],
            start_tokens=0, end_tokens=3 * self.BLOCK,
        )
        t_aborted = PromotionTicket(
            request_id="kv-aborted", digests=digests[1:4],
            start_tokens=0, end_tokens=3 * self.BLOCK,
        )
        state.tickets = [t_warm, t_aborted]

        async def _promote(ticket) -> None:  # noqa: ANN001
            await asyncio.sleep(0)
            tier.start_promotion(ticket, put_fn=lambda x: x)

        async def _abort() -> None:
            await asyncio.sleep(0)
            t_aborted.cancel()

        async def _preempt_pressure() -> None:
            # fresh demotions evict the LRU entries the tickets point at
            await asyncio.sleep(0)
            tier.submit(self._batch(state, digests[4:6]))
            await asyncio.sleep(0)
            tier.submit(self._batch(state, digests[6:8]))

        await _gather([
            spawn_task(_promote(t_warm), name="promote-warm"),
            spawn_task(_promote(t_aborted), name="promote-aborted"),
            spawn_task(_abort(), name="abort-ticket"),
            spawn_task(_preempt_pressure(), name="preempt-pressure"),
        ])
        # settle every transfer task (drain_transfers snapshots at
        # entry, so loop until the task set is quiet)
        while any(not t.done() for t in tier._tasks):  # noqa: SLF001
            await tier.drain_transfers()

    def check(self, state) -> None:  # noqa: ANN001
        tier = state.tier
        assert tier._inflight_bytes == 0, (  # noqa: SLF001
            f"in-flight demotion bytes leaked: {tier._inflight_bytes}"  # noqa: SLF001
        )
        assert not tier._inflight, (  # noqa: SLF001
            f"in-flight digests leaked: {tier._inflight}"  # noqa: SLF001
        )
        actual = sum(
            e.nbytes for e in tier._entries.values()  # noqa: SLF001
        )
        assert tier.bytes_used == actual, (
            f"byte accounting drifted: bytes_used={tier.bytes_used} "
            f"actual={actual}"
        )
        assert tier.bytes_used <= tier.budget_bytes
        for ticket in state.tickets:
            assert ticket.ready, (
                f"ticket {ticket.request_id} never reached ready — its "
                "request is parked forever"
            )
            if not ticket.failed:
                assert ticket.pages is not None
                assert (
                    ticket.end_tokens
                    == ticket.start_tokens
                    + len(ticket.pages) * tier.block_size
                ), f"ticket {ticket.request_id} span inconsistent"


# --------------------------------------------------------- 4. adapter pool


class _StubLoRAManager:
    def __init__(self, names) -> None:  # noqa: ANN001
        self._weights = {
            name: SimpleNamespace(rank=8, scaling=1.0) for name in names
        }

    def get_weights(self, name):  # noqa: ANN001, ANN201
        return self._weights.get(name)

    def pinned(self, name) -> bool:  # noqa: ANN001
        return False

    def request_disk_restore(self, name) -> bool:  # noqa: ANN001
        return False


class _DetAdapterPool(AdapterPool):
    """Real pool state machine with the device halves stubbed — the
    build/apply phases still hop through ``to_thread``, so commit
    ordering is fully explorable."""

    def _zero_stacks(self):  # noqa: ANN201
        return ("stacks", 0)

    def _build_device_blocks(self, weights):  # noqa: ANN001, ANN201
        return None, None

    def _apply(self, slot, a_dev, b_dev, scaling, rank):  # noqa: ANN001, ANN201
        return ("stacks", slot)

    def _rank_bucket(self, weights) -> int:  # noqa: ANN001
        return weights.rank


class AdapterPoolScenario(Scenario):
    """Prefetch streaming vs invalidate vs LRU eviction.

    Three adapters race into a two-slot pool; one is host-invalidated
    while its stream is in flight and one resident is evicted under
    pressure.  Slot conservation must hold on every interleaving:
    free + committed slots are always a permutation of the pool, no
    slot is double-published, and the LRU tracks exactly the committed
    residents.
    """

    name = "adapterpool-prefetch-vs-evict"

    def build(self):  # noqa: ANN201
        pool = _DetAdapterPool(
            SimpleNamespace(num_layers=2),
            max_loras=2,
            max_lora_rank=8,
            put_fn=lambda x: x,
            prefetch_concurrency=2,
        )
        pool.manager = _StubLoRAManager(["lora-a", "lora-b", "lora-c"])
        return SimpleNamespace(pool=pool, tasks=set())

    async def run(self, state) -> None:  # noqa: ANN001
        pool = state.pool

        async def _prefetch(name: str) -> None:
            await asyncio.sleep(0)
            pool.prefetch(name)

        async def _invalidate(name: str) -> None:
            await asyncio.sleep(0)
            pool.invalidate(name)

        async def _evict(name: str) -> None:
            await asyncio.sleep(0)
            pool.evict_resident(name)

        await _gather([
            spawn_task(_prefetch("lora-a"), name="prefetch-a",
                       retain=state.tasks),
            spawn_task(_prefetch("lora-b"), name="prefetch-b",
                       retain=state.tasks),
            spawn_task(_prefetch("lora-c"), name="prefetch-c",
                       retain=state.tasks),
            spawn_task(_invalidate("lora-a"), name="invalidate-a",
                       retain=state.tasks),
            spawn_task(_evict("lora-b"), name="evict-b",
                       retain=state.tasks),
        ])
        # settle in-flight streams, then retry the loser so the pool
        # ends in a steady state
        while pool._streaming:  # noqa: SLF001
            await _gather(list(pool._streaming.values()))  # noqa: SLF001
        pool.prefetch("lora-c")
        while pool._streaming:  # noqa: SLF001
            await _gather(list(pool._streaming.values()))  # noqa: SLF001

    def check(self, state) -> None:  # noqa: ANN001
        pool = state.pool
        assert not pool._streaming  # noqa: SLF001
        assert not pool._invalidated, (  # noqa: SLF001
            f"invalidation markers leaked: {pool._invalidated}"  # noqa: SLF001
        )
        committed = list(pool._slots.values())  # noqa: SLF001
        assert len(committed) == len(set(committed)), (
            f"slot double-published: {pool._slots}"  # noqa: SLF001
        )
        census = sorted(pool._free + committed)  # noqa: SLF001
        assert census == list(range(1, pool.max_loras + 1)), (
            f"slot conservation violated: free={pool._free} "  # noqa: SLF001
            f"committed={pool._slots}"  # noqa: SLF001
        )
        assert set(pool._lru) == set(pool._slots), (  # noqa: SLF001
            "LRU tracks non-residents: "
            f"lru={set(pool._lru)} slots={set(pool._slots)}"  # noqa: SLF001
        )


# ------------------------------------------------------------- 5. ledger


class DoctorScenario(Scenario):
    """Bottleneck-doctor episode lifecycle under racing evaluations.

    Two replicas' signal sources race: replica 0 sees host_bound-firing
    windows from one task and quiet windows from another (conflicting
    diagnoses of the SAME (replica, regime) key — the interleaving
    decides whether hysteresis ever accumulates OPEN_AFTER consecutive
    firing evals), while replica 1's queue_bound signals fire
    unambiguously.  On EVERY schedule the recorder's ``doctor`` event
    stream must be grammatical per (replica, regime) — open →
    evidence* → close, never unbalanced — and the profiler capture the
    host_bound episode brackets must start/stop exactly as many times
    as episodes opened/closed with it.
    """

    name = "doctor-episode-lifecycle"

    @staticmethod
    def _firing_host(replica: int) -> "ReplicaSignals":
        return ReplicaSignals(
            replica=replica, steps=16, host_gap_frac=0.6,
        )

    @staticmethod
    def _quiet(replica: int) -> "ReplicaSignals":
        return ReplicaSignals(replica=replica, steps=16)

    @staticmethod
    def _firing_queue(replica: int) -> "ReplicaSignals":
        return ReplicaSignals(
            replica=replica, steps=16, waiting=32, running=4,
            max_num_seqs=4,
        )

    def build(self):  # noqa: ANN201
        recorder = FlightRecorder()
        profiler = SimpleNamespace(starts=0, stops=0)

        def _start():  # noqa: ANN202
            profiler.starts += 1
            return {"status": "started"}

        def _stop():  # noqa: ANN202
            profiler.stops += 1
            return {"status": "stopped"}

        profiler.start = _start
        profiler.stop = _stop
        doctor = Doctor(
            record=lambda replica, **detail: recorder.record(
                "doctor", replica=replica, **detail
            ),
            profiler=lambda: profiler,
            min_interval=0.0,
        )
        return SimpleNamespace(
            recorder=recorder,
            doctor=doctor,
            profiler=profiler,
            clock=0.0,
            tasks=set(),
        )

    def _eval(self, state, signals) -> None:  # noqa: ANN001
        # one shared monotone clock across the racing tasks: the
        # doctor differences counters against it, and interleaved
        # per-task clocks would run it backwards
        state.clock += 1.0
        state.doctor.evaluate(signals, now=state.clock)

    async def run(self, state) -> None:  # noqa: ANN001
        async def _host_bound_rounds() -> None:
            for _ in range(5):
                await asyncio.sleep(0)
                self._eval(state, [self._firing_host(0)])

        async def _quiet_rounds() -> None:
            for _ in range(5):
                await asyncio.sleep(0)
                self._eval(state, [self._quiet(0)])

        async def _queue_bound_rounds() -> None:
            for _ in range(4):
                await asyncio.sleep(0)
                self._eval(state, [self._firing_queue(1)])

        await _gather([
            spawn_task(_host_bound_rounds(), name="host-bound-0",
                       retain=state.tasks),
            spawn_task(_quiet_rounds(), name="quiet-0",
                       retain=state.tasks),
            spawn_task(_queue_bound_rounds(), name="queue-bound-1",
                       retain=state.tasks),
        ])
        # deterministic quiet tail: whatever the interleaving opened
        # must close (CLOSE_AFTER quiet evals per replica), so the
        # post-run checks see a fully settled doctor
        for _ in range(4):
            self._eval(state, [self._quiet(0), self._quiet(1)])

    def check(self, state) -> None:  # noqa: ANN001
        assert not state.doctor.active, (
            f"episodes still open after quiet tail: "
            f"{[e.to_dict() for e in state.doctor.active]}"
        )
        # per-(replica, regime) grammar: open -> evidence* -> close
        open_keys: set[tuple[int, str]] = set()
        for event in state.recorder.events():
            if event["kind"] != "doctor":
                continue
            assert "request_id" not in event, (
                "doctor events are batch-scoped, never per-request"
            )
            detail = event["detail"]
            key = (detail["replica"], detail["regime"])
            phase = detail["phase"]
            if phase == "open":
                assert key not in open_keys, f"double open for {key}"
                open_keys.add(key)
            elif phase in ("evidence", "close"):
                assert key in open_keys, (
                    f"{phase} without an open episode for {key}"
                )
                if phase == "close":
                    open_keys.discard(key)
            else:  # pragma: no cover — schema guard
                raise AssertionError(f"unknown doctor phase {phase!r}")
        assert not open_keys, f"unclosed doctor streams: {open_keys}"
        # queue_bound fires 4 consecutive rounds on replica 1 — past
        # OPEN_AFTER on every schedule, so at least that episode exists
        closed = [e.regime for e in state.doctor.episodes]
        assert "queue_bound" in closed, (
            f"queue_bound never opened (closed episodes: {closed})"
        )
        # capture conservation: one start per captured open, one stop
        # per captured close — the quiet tail closed everything
        assert state.profiler.starts == state.profiler.stops, (
            f"profiler capture unbalanced: {state.profiler.starts} "
            f"starts vs {state.profiler.stops} stops"
        )
        captured = sum(
            1 for e in state.doctor.episodes if e.captured
        )
        assert state.profiler.starts == captured, (
            f"{state.profiler.starts} captures for {captured} "
            f"captured episodes"
        )

    def recorders(self, state) -> list:  # noqa: ANN001
        return [state.recorder]


class LedgerScenario(Scenario):
    """Close-at-terminal-outcome: finish vs abort vs shed racing for
    one request's single ledger record.

    Small enough for exhaustive DFS.  Each racer checks liveness,
    records its terminal event atomically with the check, then yields
    before closing — the widest legal race window.  Every schedule
    must produce exactly one close per request, a shed noted before
    the close must win the outcome, and a duplicate ``open`` must
    never mint a second record.
    """

    name = "ledger-close-at-terminal"

    def build(self):  # noqa: ANN201
        recorder = FlightRecorder()
        return SimpleNamespace(
            recorder=recorder,
            ledger=CostLedger(recorder=recorder.record),
            duplicate_open_rejected=False,
            tasks=set(),
        )

    async def run(self, state) -> None:  # noqa: ANN001
        ledger, recorder = state.ledger, state.recorder

        async def _open(rid: str) -> None:
            ledger.open(rid, tenant="t")
            recorder.record("admit", rid)

        async def _racer(rid: str, outcome: str) -> None:
            await asyncio.sleep(0)
            if ledger.get(rid) is None:
                return  # lost the race: no event, no close
            # event recorded atomically with the liveness check …
            recorder.record(outcome, rid)
            await asyncio.sleep(0)  # … then the race window
            ledger.close(rid, outcome)

        async def _shedder(rid: str) -> None:
            await asyncio.sleep(0)
            if ledger.get(rid) is None:
                return
            ledger.note_shed(rid, "ttl")
            await asyncio.sleep(0)
            ledger.close(rid, "abort")  # noted shed must win this

        async def _dup_open(rid: str) -> None:
            await asyncio.sleep(0)
            if ledger.get(rid) is None:
                # the record already closed: a same-id latecomer is a
                # NEW request, not a duplicate — vacuously fine here
                # (the TOCTOU re-check race is pinned in
                # tests/test_dettest.py)
                state.duplicate_open_rejected = True
            else:
                # atomic with the liveness check: the duplicate must be
                # refused while the first record is still open
                state.duplicate_open_rejected = (
                    ledger.open(rid, tenant="latecomer") is None
                )

        await _open("led-r1")
        await _open("led-r2")
        await _gather([
            spawn_task(_racer("led-r1", "finish"), name="finish-r1",
                       retain=state.tasks),
            spawn_task(_racer("led-r1", "abort"), name="abort-r1",
                       retain=state.tasks),
            spawn_task(_racer("led-r2", "finish"), name="finish-r2",
                       retain=state.tasks),
            spawn_task(_shedder("led-r2"), name="shed-r2",
                       retain=state.tasks),
            spawn_task(_dup_open("led-r1"), name="dup-open-r1",
                       retain=state.tasks),
        ])

    def check(self, state) -> None:  # noqa: ANN001
        ledger = state.ledger
        assert ledger.open_count == 0, (
            f"{ledger.open_count} records never closed"
        )
        assert ledger.closed_total == 2, (
            f"expected exactly 2 closes, got {ledger.closed_total}"
        )
        assert state.duplicate_open_rejected, (
            "duplicate open minted a second record"
        )
        ledger_events = {}
        for event in state.recorder.events():
            if event["kind"] == "ledger":
                rid = event["request_id"]
                ledger_events[rid] = ledger_events.get(rid, 0) + 1
        assert ledger_events == {"led-r1": 1, "led-r2": 1}, (
            f"ledger event conservation violated: {ledger_events}"
        )

    def recorders(self, state) -> list:  # noqa: ANN001
        return [state.recorder]


# ------------------------------------------- 6. kvnet staged handoffs


class KvNetScenario(Scenario):
    """Cross-host handoff COMMIT racing the peer-death adoption sweep
    (docs/CROSS_HOST.md).

    Drives the REAL :class:`~vllm_tgis_adapter_tpu.kvnet.manager.
    StagedHandoffs` ledger: a prefill peer ``A`` has staged three
    checkpoints on this host, and then — in chooser-visible order —
    each request's CKPT_COMMIT arrives, ``A`` dies (two adoption
    sweeps: peer-death notifications can duplicate), and one request
    is cancelled source-side (a DISCARD).  Every schedule must resume
    each surviving request exactly once (no lost output, no double
    promote), and a discarded request at most once — the claim flag
    flips atomically with the pop, so COMMIT-vs-sweep has exactly one
    winner.
    """

    name = "kvnet-commit-vs-adopt"

    def build(self):  # noqa: ANN201
        from vllm_tgis_adapter_tpu.kvnet.manager import StagedHandoffs

        recorder = FlightRecorder()
        staged = StagedHandoffs()
        rids = ("kn-r1", "kn-r2", "kn-r3")
        for rid in rids:
            staged.stage(SimpleNamespace(request_id=rid), source="A")
        return SimpleNamespace(
            recorder=recorder,
            staged=staged,
            rids=rids,
            promoted={rid: 0 for rid in rids},
            discarded=False,
            tasks=set(),
        )

    async def run(self, state) -> None:  # noqa: ANN001
        staged, recorder = state.staged, state.recorder

        async def _promote(rec) -> None:  # noqa: ANN001
            # the resume itself yields (queue registration, replica
            # lock) — the claim above must already have settled the
            # winner, so this window is legal
            rid = rec["ckpt"].request_id
            recorder.record("remote_handoff_in", rid, peer=rec["source"])
            await asyncio.sleep(0)
            state.promoted[rid] += 1
            recorder.record("finish", rid)
            recorder.record("ledger", rid)

        async def _commit(rid: str) -> None:
            await asyncio.sleep(0)
            rec = staged.claim(rid)
            if rec is not None:
                await _promote(rec)

        async def _sweep() -> None:
            await asyncio.sleep(0)
            recorder.record("peer_down", peer="A")
            for rec in staged.adopt_for_peer("A"):
                await _promote(rec)

        async def _discard(rid: str) -> None:
            # source-side cancel racing both the COMMIT and the sweep:
            # at most one of the three touches the record
            await asyncio.sleep(0)
            staged.discard(rid)
            state.discarded = True

        await _gather([
            spawn_task(_commit("kn-r1"), name="commit-r1",
                       retain=state.tasks),
            spawn_task(_commit("kn-r2"), name="commit-r2",
                       retain=state.tasks),
            spawn_task(_sweep(), name="peer-death-sweep-1",
                       retain=state.tasks),
            spawn_task(_sweep(), name="peer-death-sweep-2",
                       retain=state.tasks),
            spawn_task(_discard("kn-r3"), name="discard-r3",
                       retain=state.tasks),
        ])

    def check(self, state) -> None:  # noqa: ANN001
        assert state.staged.pending() == 0, (
            f"{state.staged.pending()} staged handoffs leaked past "
            "commit + adoption"
        )
        for rid in ("kn-r1", "kn-r2"):
            assert state.promoted[rid] == 1, (
                f"{rid} resumed {state.promoted[rid]} times: a "
                "COMMIT-vs-adoption schedule lost or double-promoted it"
            )
        assert state.promoted["kn-r3"] <= 1, (
            "a discarded handoff was double-promoted"
        )
        assert state.discarded, "the discard racer never ran"

    def recorders(self, state) -> list:  # noqa: ANN001
        return [state.recorder]


# ----------------------------------------------------- seeded failpoint


class SlotOvergrantFailpoint(Scenario):
    """INTENTIONALLY racy (this scenario is SUPPOSED to fail on some
    schedules): the historical grant-cancellation slot leak reduced to
    its essence — a check-then-act admission window with an await
    between the room check and the grant.  ``race_check`` uses it to
    prove the explorer finds seeded races, and that a recorded failing
    seed replays byte-for-byte."""

    name = "failpoint-slot-overgrant"
    SLOTS = 2

    def build(self):  # noqa: ANN201
        return SimpleNamespace(used=0, peak=0, tasks=set())

    async def run(self, state) -> None:  # noqa: ANN001
        async def _worker() -> None:
            if state.used < self.SLOTS:  # check …
                await asyncio.sleep(0)  # … the buggy window …
                state.used += 1  # … act
                state.peak = max(state.peak, state.used)
                await asyncio.sleep(0)
                state.used -= 1

        await _gather([
            spawn_task(_worker(), name=f"worker-{i}", retain=state.tasks)
            for i in range(3)
        ])

    def check(self, state) -> None:  # noqa: ANN001
        assert state.peak <= self.SLOTS, (
            f"admission over-grant: {state.peak} slots in use with a "
            f"window of {self.SLOTS} (the check-then-act race fired)"
        )


SCENARIOS = [
    FrontDoorScenario(),
    SupervisorScenario(),
    KvTierScenario(),
    AdapterPoolScenario(),
    KvNetScenario(),
    # DoctorScenario rides BEFORE LedgerScenario: race_check's
    # exhaustive-DFS pass assumes SCENARIOS[-1] is the small ledger
    # scenario
    DoctorScenario(),
    LedgerScenario(),
]

FAILPOINT = SlotOvergrantFailpoint()
