"""Schedule explorer: many schedules per scenario, replayable failures.

Two exploration modes over a :class:`~tools.dettest.scenarios.Scenario`:

* :func:`explore` — run the scenario under K seeds
  (:class:`SeededChooser`); the workhorse for scenarios whose schedule
  space is too large to enumerate.
* :func:`explore_exhaustive` — bounded co-ready-permutation DFS
  (:class:`PrefixChooser` backtracking): enumerate EVERY distinct
  schedule of a small scenario up to a budget.

Every explored schedule runs the scenario's own invariant ``check`` AND
replays each recorder's per-request event streams through the lifecycle
grammar (:func:`~tools.dettest.lifecycle_grammar.verify_request_stream`)
— a schedule that produces a grammatically impossible stream fails even
if the scenario's explicit invariants missed it.

A failure is an artifact, not a flake: the :class:`Failure` carries the
seed (or DFS prefix) and the canonical ``format_trace`` rendering, and
:func:`replay` re-runs it — by seed, or exactly by trace via
:class:`TraceChooser` — producing the same schedule byte-for-byte.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from tools.dettest import lifecycle_grammar
from tools.dettest.loop import (
    DEFAULT_MAX_STEPS,
    DEFAULT_TIME_LIMIT_S,
    Chooser,
    PrefixChooser,
    ReplayDivergence,
    SeededChooser,
    TraceChooser,
    det_run,
    format_trace,
)

__all__ = [
    "Failure",
    "Report",
    "explore",
    "explore_exhaustive",
    "parse_trace",
    "replay",
    "run_schedule",
]


@dataclasses.dataclass
class Failure:
    """One failing schedule, with everything needed to reproduce it."""

    scenario: str
    seed: Optional[int]  # None for DFS-enumerated schedules
    prefix: Optional[list[int]]  # DFS choice prefix when seed is None
    trace: str  # canonical format_trace rendering
    error: str  # "ErrorType: message"

    def describe(self) -> str:
        how = (
            f"seed={self.seed}"
            if self.seed is not None
            else f"prefix={self.prefix}"
        )
        return (
            f"{self.scenario}[{how}]: {self.error}\n  schedule: {self.trace}"
        )


@dataclasses.dataclass
class Report:
    """Outcome of exploring one scenario."""

    scenario: str
    schedules: int = 0  # schedules actually run
    distinct: set[str] = dataclasses.field(default_factory=set)
    failures: list[Failure] = dataclasses.field(default_factory=list)
    exhausted: bool = False  # DFS enumerated the whole space

    @property
    def distinct_count(self) -> int:
        return len(self.distinct)

    @property
    def ok(self) -> bool:
        return not self.failures


def parse_trace(text: str) -> list[tuple[int, int, str]]:
    """Inverse of ``format_trace`` (labels may not contain ``;``)."""
    out: list[tuple[int, int, str]] = []
    if not text:
        return out
    for part in text.split(";"):
        n, idx, label = part.split(":", 2)
        out.append((int(n), int(idx), label))
    return out


def _verify_grammar(scenario, state) -> None:  # noqa: ANN001
    """Replay each recorder's per-request kind streams through the DFA."""
    for recorder in scenario.recorders(state):
        streams: dict[str, list[str]] = {}
        for event in recorder._events:  # noqa: SLF001 — explorer owns this view
            kind, request_id = event[3], event[4]
            if request_id is not None:
                streams.setdefault(request_id, []).append(kind)
        for request_id, kinds in streams.items():
            lifecycle_grammar.verify_request_stream(kinds, request_id)


def run_schedule(
    scenario,  # noqa: ANN001
    chooser: Chooser,
    *,
    max_steps: int = DEFAULT_MAX_STEPS,
    time_limit: float = DEFAULT_TIME_LIMIT_S,
) -> tuple[str, Optional[str]]:
    """Run one schedule of ``scenario`` under ``chooser``; returns the
    canonical trace and the failure string (None = all invariants held).
    ``ReplayDivergence`` propagates — a divergent replay/DFS prefix is a
    nondeterministic scenario, which is a bug in the harness, not a
    finding."""
    state = scenario.build()
    error: Optional[str] = None
    try:
        det_run(
            lambda: scenario.run(state),
            chooser=chooser,
            max_steps=max_steps,
            time_limit=time_limit,
        )
        scenario.check(state)
        _verify_grammar(scenario, state)
    except ReplayDivergence:
        raise
    except Exception as exc:  # noqa: BLE001 — any failure is a finding
        error = f"{type(exc).__name__}: {exc}"
    return format_trace(chooser.trace), error


def explore(
    scenario,  # noqa: ANN001
    *,
    seeds: Iterable[int],
    max_steps: int = DEFAULT_MAX_STEPS,
    time_limit: float = DEFAULT_TIME_LIMIT_S,
) -> Report:
    """Run ``scenario`` once per seed; collect distinct schedules and
    failing schedules."""
    report = Report(scenario=scenario.name)
    for seed in seeds:
        chooser = SeededChooser(seed)
        trace, error = run_schedule(
            scenario, chooser, max_steps=max_steps, time_limit=time_limit
        )
        report.schedules += 1
        report.distinct.add(trace)
        if error is not None:
            report.failures.append(
                Failure(
                    scenario=scenario.name,
                    seed=seed,
                    prefix=None,
                    trace=trace,
                    error=error,
                )
            )
    return report


def explore_exhaustive(
    scenario,  # noqa: ANN001
    *,
    max_schedules: int = 2000,
    max_steps: int = DEFAULT_MAX_STEPS,
    time_limit: float = DEFAULT_TIME_LIMIT_S,
) -> Report:
    """Enumerate distinct schedules by co-ready-permutation DFS.

    Each run follows a choice prefix then picks index 0; backtracking
    bumps the deepest non-exhausted choice.  ``exhausted=True`` on the
    report means the FULL schedule space was covered within the budget.
    """
    report = Report(scenario=scenario.name)
    prefix: list[int] = []
    while report.schedules < max_schedules:
        chooser = PrefixChooser(prefix)
        trace, error = run_schedule(
            scenario, chooser, max_steps=max_steps, time_limit=time_limit
        )
        report.schedules += 1
        report.distinct.add(trace)
        if error is not None:
            report.failures.append(
                Failure(
                    scenario=scenario.name,
                    seed=None,
                    prefix=[idx for _, idx in chooser.taken],
                    trace=trace,
                    error=error,
                )
            )
        # deepest choice with siblings left becomes the next prefix
        taken = list(chooser.taken)
        while taken and taken[-1][1] + 1 >= taken[-1][0]:
            taken.pop()
        if not taken:
            report.exhausted = True
            break
        prefix = [idx for _, idx in taken[:-1]] + [taken[-1][1] + 1]
    return report


def replay(
    scenario,  # noqa: ANN001
    *,
    seed: Optional[int] = None,
    trace: Optional[str] = None,
    max_steps: int = DEFAULT_MAX_STEPS,
    time_limit: float = DEFAULT_TIME_LIMIT_S,
) -> tuple[str, Optional[str]]:
    """Reproduce one schedule: by ``seed`` (same PRNG, same schedule) or
    exactly by recorded ``trace`` (divergence raises).  Returns the same
    ``(trace, error)`` pair as the original run — byte-for-byte."""
    if (seed is None) == (trace is None):
        raise ValueError("replay needs exactly one of seed= or trace=")
    chooser: Chooser = (
        SeededChooser(seed)
        if seed is not None
        else TraceChooser(parse_trace(trace or ""))
    )
    return run_schedule(
        scenario, chooser, max_steps=max_steps, time_limit=time_limit
    )
