"""dettest: deterministic async-schedule exploration for the control plane.

Every hard bug in this repo's history was an asyncio *interleaving*
race (the grant-cancellation slot leak, the duplicate-request_id
TOCTOU, the bpo-42130 pump hang, the shed-vs-stream terminal race).
tpulint proves lock discipline statically but cannot see
schedule-dependent bugs; this package makes them a deterministic,
replayable, checked-in gate instead of review luck:

* ``loop``     — ``DetLoop``, a seeded deterministic event loop on
                 virtual time, plus the schedule choosers;
* ``explorer`` — run a scenario under K seeds (or bounded co-ready
                 permutation DFS), record failing schedules, replay
                 them byte-for-byte;
* ``lifecycle_grammar`` — the reviewed ``LIFECYCLE_MANIFEST``: the
                 per-request flight-recorder event DFA and the engine
                 lifecycle machine (enforced statically by tpulint
                 TPL511/TPL512, at runtime by ``TGIS_TPU_SANITIZE=1``,
                 and on every explored schedule by the explorer);
* ``scenarios`` — the concurrency-critical control-plane scenarios
                 (frontdoor, supervisor, kv-tier, adapter-pool,
                 ledger) with their invariants;
* ``race_check`` — the ``nox -s race_check`` gate entry point.

See docs/STATIC_ANALYSIS.md "Deterministic schedule exploration".
"""
