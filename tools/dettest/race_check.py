"""``nox -s race_check``: the deterministic schedule-exploration gate.

One run, four proofs, deterministic stdout (two runs print identical
schedule counts — there is no wall-clock, PRNG or address-dependent
state anywhere in the output):

1. the lifecycle-grammar manifest is internally consistent and every
   per-request kind it declares exists in ``flight_recorder.EVENT_KINDS``;
2. every control-plane scenario holds ALL its invariants (and produces
   grammatically legal event streams) across ``SEEDS_PER_SCENARIO``
   seeded schedules, with at least ``MIN_DISTINCT`` distinct schedules
   actually explored per scenario;
3. the smallest scenario additionally survives a bounded co-ready-
   permutation DFS (systematic coverage, not just sampling);
4. the harness FINDS seeded races: the intentional failpoint scenario
   must fail under some seed, the recorded failing seed must reproduce
   the exact same failing schedule byte-for-byte twice, and the
   recorded trace must replay exactly through a ``TraceChooser``.

Exit status 0 = gate green.  Budget: well under 120s on one core.
"""

from __future__ import annotations

import logging
import os
import sys

SEEDS_PER_SCENARIO = 60
MIN_DISTINCT = 50
DFS_BUDGET = 300
FAILPOINT_SEEDS = 40


def main(argv=None) -> int:  # noqa: ANN001
    # the runtime sanitizer (and so the grammar tracker) must be live
    # on every explored schedule; silence the control plane's expected
    # shed/recovery noise so stdout stays byte-deterministic
    os.environ.setdefault("TGIS_TPU_SANITIZE", "1")
    logging.disable(logging.CRITICAL)

    from vllm_tgis_adapter_tpu.flight_recorder import EVENT_KINDS

    from tools.dettest import explorer, lifecycle_grammar, scenarios

    ok = True

    def say(line: str) -> None:
        print(line)

    say("dettest race_check")

    # -- 1. the manifest itself -------------------------------------
    problems = lifecycle_grammar.self_check()
    for problem in problems:
        ok = False
        say(f"FAIL grammar manifest: {problem}")
    drift = lifecycle_grammar.all_kinds() ^ set(EVENT_KINDS)
    if drift:
        ok = False
        say(
            f"FAIL grammar manifest and flight_recorder.EVENT_KINDS "
            f"disagree on kind(s): {sorted(drift)}"
        )
    if ok:
        say(
            f"grammar: manifest OK "
            f"({len(lifecycle_grammar.request_kinds())} request kinds, "
            f"{len(lifecycle_grammar.engine_edges())} lifecycle edges)"
        )

    # -- 2. seeded exploration of every scenario --------------------
    total_distinct = 0
    for scenario in scenarios.SCENARIOS:
        report = explorer.explore(
            scenario, seeds=range(SEEDS_PER_SCENARIO)
        )
        total_distinct += report.distinct_count
        say(
            f"{scenario.name}: {report.schedules} schedules, "
            f"{report.distinct_count} distinct, "
            f"{len(report.failures)} failures"
        )
        if report.distinct_count < MIN_DISTINCT:
            ok = False
            say(
                f"FAIL {scenario.name}: only {report.distinct_count} "
                f"distinct schedules (< {MIN_DISTINCT}) — the scenario "
                "lost its concurrency"
            )
        for failure in report.failures:
            ok = False
            say("FAIL " + failure.describe())

    # -- 3. bounded DFS over the smallest scenario ------------------
    ledger_scenario = scenarios.SCENARIOS[-1]
    dfs = explorer.explore_exhaustive(
        ledger_scenario, max_schedules=DFS_BUDGET
    )
    say(
        f"{ledger_scenario.name}[dfs]: {dfs.schedules} schedules "
        f"({'exhausted' if dfs.exhausted else 'bounded'}), "
        f"{len(dfs.failures)} failures"
    )
    for failure in dfs.failures:
        ok = False
        say("FAIL " + failure.describe())

    # -- 4. the harness finds (and replays) seeded races ------------
    fp = scenarios.FAILPOINT
    fp_report = explorer.explore(fp, seeds=range(FAILPOINT_SEEDS))
    say(
        f"{fp.name}: {len(fp_report.failures)}/{fp_report.schedules} "
        "schedules trip the seeded race"
    )
    if not fp_report.failures:
        ok = False
        say(
            f"FAIL {fp.name}: no seed out of {FAILPOINT_SEEDS} tripped "
            "the intentional race — the explorer is not actually "
            "permuting schedules"
        )
    else:
        failing = fp_report.failures[0]
        say(f"  failing seed {failing.seed}: {failing.error}")
        say(f"  schedule: {failing.trace}")
        first = explorer.replay(fp, seed=failing.seed)
        second = explorer.replay(fp, seed=failing.seed)
        if not (
            first == second
            and first == (failing.trace, failing.error)
        ):
            ok = False
            say(
                f"FAIL {fp.name}: seed {failing.seed} did not replay "
                f"byte-for-byte (got {first!r} then {second!r}, "
                f"recorded {(failing.trace, failing.error)!r})"
            )
        else:
            say("  seed replay x2: byte-identical")
        try:
            replayed = explorer.replay(fp, trace=failing.trace)
        except explorer.ReplayDivergence as exc:
            ok = False
            say(f"FAIL {fp.name}: trace replay diverged: {exc}")
        else:
            if replayed != (failing.trace, failing.error):
                ok = False
                say(
                    f"FAIL {fp.name}: trace replay produced "
                    f"{replayed!r}, recorded "
                    f"{(failing.trace, failing.error)!r}"
                )
            else:
                say("  trace replay: byte-identical")

    if ok:
        say(
            f"race_check: PASS ({len(scenarios.SCENARIOS)} scenarios, "
            f"{total_distinct} distinct schedules, all invariants held)"
        )
        return 0
    say("race_check: FAIL")
    return 1


if __name__ == "__main__":
    sys.exit(main())
