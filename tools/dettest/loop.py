"""``DetLoop``: a deterministic, seeded asyncio event loop on virtual time.

The real selector loop runs co-ready callbacks in FIFO order, so one
process run explores exactly one interleaving.  ``DetLoop`` is an
``asyncio.AbstractEventLoop`` whose *only* nondeterminism source is an
injected :class:`Chooser`: whenever more than one callback is ready, the
chooser picks which runs next.  A :class:`SeededChooser` draws from a
seeded PRNG (K seeds = K schedules); a :class:`TraceChooser` replays a
recorded schedule exactly (a race is a reproducible artifact, not a
flake); a :class:`PrefixChooser` drives the explorer's bounded
co-ready-permutation DFS.

Time is virtual: ``loop.time()`` only advances when the ready set is
empty, jumping straight to the earliest timer — ``sleep``/``wait_for``/
TTL timeouts cost zero wall-clock.  ``run_in_executor`` (and therefore
``asyncio.to_thread``) schedules the function as an ordinary loop
callback instead of a worker thread, so thread-offloaded sections are
single-threaded, deterministic, and *visible to the chooser* as
schedule points — exactly the suspension points where production races
live.

Scheduling decisions with a single ready callback are forced and not
recorded; the recorded trace is the list of genuine ``(n_ready,
chosen_index, label)`` choices, which is the schedule's identity for
distinct-schedule counting and byte-for-byte replay.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import heapq
import random
import time as _time
from typing import Any, Callable, Optional

__all__ = [
    "Chooser",
    "DeadlockError",
    "DetLoop",
    "HangError",
    "PrefixChooser",
    "ReplayDivergence",
    "SeededChooser",
    "TraceChooser",
    "det_run",
    "format_trace",
    "virtual_wall_clock",
]

# livelock guards: a scenario that spins past either bound is a bug in
# the scenario (or a genuine livelock) — fail loudly instead of hanging
# the gate
DEFAULT_MAX_STEPS = 200_000
DEFAULT_TIME_LIMIT_S = 600.0


class DeadlockError(RuntimeError):
    """Ready set and timer heap both empty with work still pending."""


class HangError(RuntimeError):
    """Virtual time or step budget exhausted (livelock guard)."""


class ReplayDivergence(RuntimeError):
    """A trace replay saw a different ready-set shape than recorded —
    the scenario is not deterministic for its seed."""


# --------------------------------------------------------------- choosers


class Chooser:
    """Schedule oracle: ``choose(n, labels)`` picks which of the ``n``
    co-ready callbacks runs next.  Every genuine choice (n > 1) is
    appended to ``trace`` as ``(n, index, label)``."""

    def __init__(self) -> None:
        self.trace: list[tuple[int, int, str]] = []

    def choose(self, n: int, labels: list[str]) -> int:  # pragma: no cover
        raise NotImplementedError

    def _record(self, n: int, idx: int, labels: list[str]) -> int:
        self.trace.append((n, idx, labels[idx]))
        return idx


class SeededChooser(Chooser):
    """Uniform choice from a seeded PRNG: one seed, one schedule."""

    def __init__(self, seed: int):
        super().__init__()
        self.seed = seed
        self._rng = random.Random(seed)

    def choose(self, n: int, labels: list[str]) -> int:
        return self._record(n, self._rng.randrange(n), labels)


class TraceChooser(Chooser):
    """Replay a recorded trace exactly; raise on any divergence."""

    def __init__(self, trace: list[tuple[int, int, str]]):
        super().__init__()
        self._replay = list(trace)
        self._pos = 0

    def choose(self, n: int, labels: list[str]) -> int:
        if self._pos >= len(self._replay):
            raise ReplayDivergence(
                f"trace exhausted at choice {self._pos}: live run has an "
                f"extra {n}-way choice over {labels}"
            )
        rec_n, rec_idx, rec_label = self._replay[self._pos]
        self._pos += 1
        if rec_n != n or rec_idx >= n:
            raise ReplayDivergence(
                f"choice {self._pos - 1}: recorded {rec_n}-way pick of "
                f"{rec_label!r}, live run offers {n}-way {labels}"
            )
        return self._record(n, rec_idx, labels)


class PrefixChooser(Chooser):
    """DFS driver for the bounded co-ready-permutation mode: follow a
    fixed choice prefix, then always pick index 0.  The explorer
    backtracks by bumping the last non-exhausted prefix position."""

    def __init__(self, prefix: list[int]):
        super().__init__()
        self._prefix = list(prefix)
        self._pos = 0
        # (n, idx) actually taken at each choice — the backtrack input
        self.taken: list[tuple[int, int]] = []

    def choose(self, n: int, labels: list[str]) -> int:
        idx = self._prefix[self._pos] if self._pos < len(self._prefix) else 0
        self._pos += 1
        if idx >= n:
            raise ReplayDivergence(
                f"DFS prefix position {self._pos - 1} wants index {idx} "
                f"but only {n} callbacks are ready — scenario is not "
                "deterministic across runs"
            )
        self.taken.append((n, idx))
        return self._record(n, idx, labels)


def format_trace(trace: list[tuple[int, int, str]]) -> str:
    """Canonical byte-stable rendering of a schedule trace (the
    acceptance criterion's byte-for-byte replay comparison)."""
    return ";".join(f"{n}:{idx}:{label}" for n, idx, label in trace)


# ------------------------------------------------------------------- loop


def _callback_label(callback: Callable) -> str:
    """Deterministic, address-free display name for a ready callback."""
    owner = getattr(callback, "__self__", None)
    if isinstance(owner, asyncio.Task):
        return owner.get_name()
    if isinstance(owner, asyncio.Future):
        return "future"
    if isinstance(callback, functools.partial):
        inner = callback.func
        # asyncio.to_thread wraps as partial(context.run, func, ...)
        if getattr(inner, "__name__", "") == "run" and callback.args:
            inner = callback.args[0]
            if isinstance(inner, functools.partial):
                inner = inner.func
        return getattr(inner, "__qualname__", None) or type(inner).__name__
    return (
        getattr(callback, "__qualname__", None)
        or getattr(callback, "__name__", None)
        or type(callback).__name__
    )


class DetLoop(asyncio.AbstractEventLoop):
    """Deterministic event loop: single-threaded, seeded, virtual-time.

    Supports exactly the surface the control plane uses — ``call_soon``
    / ``call_later`` / ``call_at``, tasks, futures, and an inline
    ``run_in_executor`` — and deliberately nothing selector-based (no
    sockets, no signals, no subprocesses): scenarios exercise host-side
    state machines, not I/O.
    """

    def __init__(
        self,
        chooser: Optional[Chooser] = None,
        *,
        max_steps: int = DEFAULT_MAX_STEPS,
        time_limit: float = DEFAULT_TIME_LIMIT_S,
    ):
        self.chooser = chooser if chooser is not None else SeededChooser(0)
        self.max_steps = max_steps
        self.time_limit = time_limit
        self._time = 0.0
        self._ready: list[asyncio.Handle] = []
        self._timers: list[tuple[float, int, asyncio.TimerHandle]] = []
        self._tiebreak = 0  # FIFO order within one timer deadline
        self._task_counter = 0  # deterministic default task names
        self._steps = 0
        self._running = False
        self._stopping = False
        self._closed = False
        self._debug = False
        #: contexts passed to call_exception_handler during the run
        #: (unretrieved task exceptions, callback failures)
        self.exceptions: list[dict] = []

    # ------------------------------------------------------------- clock

    def time(self) -> float:
        return self._time

    # --------------------------------------------------------- callbacks

    def _check_closed(self) -> None:
        if self._closed:
            raise RuntimeError("DetLoop is closed")

    def call_soon(self, callback, *args, context=None):  # noqa: ANN001, ANN002
        self._check_closed()
        handle = asyncio.Handle(callback, args, self, context)
        self._ready.append(handle)
        return handle

    # single-threaded by construction (run_in_executor is inline), so
    # threadsafe scheduling is ordinary scheduling
    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None):  # noqa: ANN001, ANN002
        return self.call_at(
            self._time + max(0.0, delay), callback, *args, context=context
        )

    def call_at(self, when, callback, *args, context=None):  # noqa: ANN001, ANN002
        self._check_closed()
        handle = asyncio.TimerHandle(when, callback, args, self, context)
        self._tiebreak += 1
        heapq.heappush(self._timers, (when, self._tiebreak, handle))
        return handle

    def _timer_handle_cancelled(self, handle) -> None:  # noqa: ANN001
        pass  # cancelled handles are skipped at pop time

    # ----------------------------------------------------- futures/tasks

    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None, context=None):  # noqa: ANN001
        return self._new_task(coro, name=name)

    def _new_task(self, coro, name=None):  # noqa: ANN001
        self._check_closed()
        task = asyncio.Task(coro, loop=self, name=name)
        if name is None:
            # override CPython's process-global Task-N counter with a
            # per-loop one: labels (and so traces) must not depend on
            # how many tasks earlier runs created
            self._task_counter += 1
            task.set_name(f"dtask-{self._task_counter}")
        return task

    def run_in_executor(self, executor, func, *args):  # noqa: ANN001, ANN002
        """Run ``func`` as a loop callback instead of a worker thread:
        deterministic, and a genuine schedule point the chooser can
        reorder against other ready work (where to_thread races live)."""
        self._check_closed()
        future = self.create_future()

        def _invoke() -> None:
            try:
                result = func(*args)
            except BaseException as exc:  # noqa: BLE001 — routed to the awaiter
                if not future.cancelled():
                    future.set_exception(exc)
            else:
                if not future.cancelled():
                    future.set_result(result)

        _invoke.__qualname__ = f"executor:{_callback_label(func)}"
        self.call_soon(_invoke)
        return future

    # ----------------------------------------------------------- running

    def is_running(self) -> bool:
        return self._running

    def is_closed(self) -> bool:
        return self._closed

    def stop(self) -> None:
        self._stopping = True

    def close(self) -> None:
        if self._running:
            raise RuntimeError("cannot close a running DetLoop")
        self._closed = True
        self._ready.clear()
        self._timers.clear()

    async def shutdown_asyncgens(self) -> None:
        pass

    async def shutdown_default_executor(self) -> None:
        pass

    def run_until_complete(self, future):  # noqa: ANN001
        self._check_closed()
        if asyncio.iscoroutine(future):
            future = self._new_task(future, name="det-main")
        if not asyncio.isfuture(future):
            raise TypeError(f"coroutine or Future required, got {future!r}")

        def _done(_fut) -> None:  # noqa: ANN001
            self.stop()

        future.add_done_callback(_done)
        try:
            self.run_forever()
        finally:
            future.remove_done_callback(_done)
        if not future.done():
            raise DeadlockError(
                "ready set and timer heap drained with the main future "
                "still pending — tasks are deadlocked on each other"
            )
        return future.result()

    def run_forever(self) -> None:
        self._check_closed()
        if self._running:
            raise RuntimeError("DetLoop is already running")
        self._running = True
        self._stopping = False
        asyncio.events._set_running_loop(self)  # noqa: SLF001 — the loop-runner contract
        try:
            while not self._stopping:
                if not self._run_once():
                    break
        finally:
            asyncio.events._set_running_loop(None)  # noqa: SLF001
            self._running = False

    # one scheduling step; False = nothing left to run
    def _run_once(self) -> bool:
        self._steps += 1
        if self._steps > self.max_steps:
            raise HangError(
                f"DetLoop exceeded {self.max_steps} steps at virtual "
                f"time {self._time:.3f}s — livelock in the scenario"
            )
        self._ready = [h for h in self._ready if not h.cancelled()]
        if not self._ready:
            if not self._advance_to_next_timer():
                return False
            self._ready = [h for h in self._ready if not h.cancelled()]
            if not self._ready:
                return True  # popped timers were all cancelled
        self._pump_due_timers()
        n = len(self._ready)
        if n == 1:
            handle = self._ready.pop(0)  # forced: not a choice
        else:
            labels = [_callback_label(h._callback) for h in self._ready]  # noqa: SLF001
            handle = self._ready.pop(self.chooser.choose(n, labels))
        handle._run()  # noqa: SLF001 — the loop-runner contract
        return True

    def _pump_due_timers(self) -> None:
        while self._timers and self._timers[0][0] <= self._time:
            _, _, handle = heapq.heappop(self._timers)
            if not handle.cancelled():
                self._ready.append(handle)

    def _advance_to_next_timer(self) -> bool:
        while self._timers:
            when, _, handle = heapq.heappop(self._timers)
            if handle.cancelled():
                continue
            if when > self.time_limit:
                raise HangError(
                    f"DetLoop virtual time would pass {self.time_limit}s "
                    f"(next timer at {when:.3f}s) — the scenario is "
                    "waiting on something that never happens"
                )
            self._time = max(self._time, when)
            self._ready.append(handle)
            return True
        return False

    def drain_pending(self) -> None:
        """Cancel every still-pending task and run them to completion —
        scenarios end with a quiet loop, so no nondeterministic
        GC-time "task was destroyed pending" noise survives a run."""
        for _ in range(64):  # cancellation can spawn cleanup tasks
            pending = [
                t for t in asyncio.all_tasks(self) if not t.done()
            ]
            if not pending:
                return
            for task in pending:
                task.cancel()
            gather = asyncio.gather(*pending, return_exceptions=True)
            self.run_until_complete(gather)

    # -------------------------------------------------------- diagnostics

    def get_debug(self) -> bool:
        return self._debug

    def set_debug(self, enabled: bool) -> None:
        self._debug = enabled

    def default_exception_handler(self, context) -> None:  # noqa: ANN001
        self.exceptions.append(context)

    def call_exception_handler(self, context) -> None:  # noqa: ANN001
        self.exceptions.append(context)


# ---------------------------------------------------------- wall clock


@contextlib.contextmanager
def virtual_wall_clock(loop: DetLoop):
    """Patch ``time.time``/``time.monotonic`` to follow the loop's
    virtual clock (each keeps its own base).  Admission TTLs and queue
    ages read ``time.time`` and LRU/throughput state reads
    ``time.monotonic`` — under exploration both must advance with
    virtual sleeps, not the wall.  ``perf_counter`` and the ``*_ns``
    stamps stay real (they feed logs/metrics, never control flow)."""
    wall_base = _time.time()
    mono_base = _time.monotonic()
    real_time, real_mono = _time.time, _time.monotonic
    _time.time = lambda: wall_base + loop.time()
    _time.monotonic = lambda: mono_base + loop.time()
    try:
        yield
    finally:
        _time.time = real_time
        _time.monotonic = real_mono


# ----------------------------------------------------------------- runner


def det_run(
    main_factory: Callable[[], Any],
    *,
    chooser: Optional[Chooser] = None,
    seed: int = 0,
    max_steps: int = DEFAULT_MAX_STEPS,
    time_limit: float = DEFAULT_TIME_LIMIT_S,
) -> tuple[Any, list[tuple[int, int, str]]]:
    """Run ``main_factory()`` (a coroutine factory) to completion on a
    fresh ``DetLoop`` under a virtual wall clock.  Returns ``(result,
    schedule_trace)``.  Unhandled exceptions from background callbacks
    or tasks re-raise after the main coroutine finishes — a scenario
    whose spawned task died must fail, not pass silently."""
    if chooser is None:
        chooser = SeededChooser(seed)
    loop = DetLoop(chooser, max_steps=max_steps, time_limit=time_limit)
    try:
        with virtual_wall_clock(loop):
            result = loop.run_until_complete(main_factory())
            loop.drain_pending()
    finally:
        loop.close()
    fatal = [
        ctx
        for ctx in loop.exceptions
        if not isinstance(ctx.get("exception"), asyncio.CancelledError)
    ]
    if fatal:
        first = fatal[0]
        exc = first.get("exception")
        raise RuntimeError(
            f"unhandled exception in background callback/task: "
            f"{first.get('message', '')} ({type(exc).__name__ if exc else '?'}: {exc})"
        ) from exc
    return result, chooser.trace
