"""``python -m tools.tpulint`` entry point."""

from tools.tpulint.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
