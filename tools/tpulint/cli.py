"""tpulint CLI — scriptable gate in the tools/obs_check.py style.

Exit codes: 0 = clean, 1 = findings, 2 = internal/usage error.

Run as ``python -m tools.tpulint [paths...]`` or directly as
``python tools/tpulint/cli.py [paths...]`` from the repo root.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

if __package__ in (None, ""):  # direct-file invocation
    sys.path.insert(0, str(Path(__file__).resolve().parents[2]))

from tools.tpulint import config  # noqa: E402
from tools.tpulint.analyzer import (  # noqa: E402
    Finding,
    analyze_project,
)


def iter_py_files(paths: list[str]) -> list[Path]:
    files: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py" and p.is_file():
            files.append(p)
        else:
            raise FileNotFoundError(f"not a python file or directory: {raw}")
    return files


def _report_text(findings: list[Finding], n_files: int, verbose: bool) -> None:
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    for f in active:
        print(f.render())
    if verbose:
        for f in suppressed:
            print(f.render())
    print(
        f"tpulint: {len(active)} finding(s), {len(suppressed)} "
        f"suppressed-with-reason, across {n_files} file(s)"
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="AST hazard analyzer for JAX/TPU serving code "
                    "(recompile / host-sync / async-blocking).",
    )
    parser.add_argument(
        "paths", nargs="*", default=["vllm_tgis_adapter_tpu"],
        help="files or directories to analyze (default: the package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit 0",
    )
    parser.add_argument(
        "--write-lattice", action="store_true",
        help="regenerate tools/tpulint/lattice_manifest.json from the "
             "given paths (after an INTENTIONAL jit-entry change; "
             "mirrors perf_check's --write convention) and exit 0",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json includes suppressed findings)",
    )
    parser.add_argument(
        "-v", "--verbose", action="store_true",
        help="also print suppressed findings",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, desc in sorted(config.RULES.items()):
            print(f"{code}  {desc}")
        return 0

    try:
        files = iter_py_files(args.paths or ["vllm_tgis_adapter_tpu"])
    except FileNotFoundError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2

    if args.write_lattice:
        from tools.tpulint.lattice import write_manifest

        target = write_manifest([Path(p) for p in args.paths])
        print(f"tpulint: wrote compile-lattice manifest to {target}")
        return 0

    try:
        findings: list[Finding] = analyze_project(files)
    except SyntaxError as e:
        print(f"tpulint: cannot parse: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(
            [dataclass_dict(f) for f in findings], indent=2
        ))
    else:
        _report_text(findings, len(files), args.verbose)
    return 1 if any(not f.suppressed for f in findings) else 0


def dataclass_dict(f: Finding) -> dict:
    return {
        "path": f.path, "line": f.line, "col": f.col, "code": f.code,
        "message": f.message, "suppressed": f.suppressed,
        "reason": f.reason,
    }


if __name__ == "__main__":
    raise SystemExit(main())
