"""The tpulint AST passes.

Two passes per module:

* **Pass A (jit index)** — find every jitted function: ``@jax.jit`` /
  ``@partial(jax.jit, ...)`` decoration, call-site wrapping
  (``jax.jit(fn, ...)``, ``shard_map(fn, ...)``, including through
  ``functools.partial``), and the cross-module registry
  (config.JIT_REGISTRY).  Static parameters are resolved from
  ``static_argnums``/``static_argnames`` and partial-bound arguments.
* **Pass B (checker)** — a scoped walk that applies the TPL rules with
  the jit index, the module's step-loop classification, and the
  enclosing-function kind (async vs sync) as context.

Suppressions are line-local comments::

    expr  # tpulint: disable=TPL202(reason), TPL201(other reason)

and apply to their own line and the line below (for statements too long
to carry a trailing comment).  A disable entry without a reason raises
TPL000 instead of suppressing anything.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Optional, Union

from tools.tpulint import (
    astutil,
    concurrency,
    config,
    lattice,
    lifecycle,
    resources,
)

_DISABLE_RE = re.compile(r"#\s*tpulint:\s*disable=(?P<body>.+)$")
# lazy reason + lookahead to the next entry or end-of-comment, so
# reasons may contain (balanced) parentheses and commas
_ENTRY_RE = re.compile(
    r"(TPL\d{3})\s*(?:\((.*?)\))?(?=\s*(?:,\s*TPL\d{3}|$))"
)

_FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]


@dataclasses.dataclass
class Finding:
    """One rule hit; ``suppressed`` hits stay in the list for reporting."""

    path: str
    line: int
    col: int
    code: str
    message: str
    suppressed: bool = False
    reason: Optional[str] = None

    def render(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}{tag}"


# --------------------------------------------------------------- helpers


def _is_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` / bare ``jit`` (imported from jax)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return isinstance(node, ast.Name) and node.id == "jit"


def _is_partial_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "partial"
    return isinstance(node, ast.Name) and node.id == "partial"


def _is_shard_map_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr == "shard_map"
    return isinstance(node, ast.Name) and node.id == "shard_map"


def _const_ints(node: Optional[ast.expr]) -> list[int]:
    """Literal ints from ``static_argnums=(9, 10)`` / ``=9`` forms."""
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _const_strs(node: Optional[ast.expr]) -> list[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _positional_params(fn: _FuncNode) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _all_params(fn: _FuncNode) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _identifiers(node: ast.expr) -> Iterable[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _mentions_shape(node: ast.expr) -> bool:
    return any(
        isinstance(sub, ast.Attribute) and sub.attr == "shape"
        for sub in ast.walk(node)
    )


def _device_hinted(node: ast.expr) -> bool:
    return any(config.DEVICE_HINTS.search(name) for name in _identifiers(node))


def _call_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _np_rooted(func: ast.expr) -> bool:
    return (
        isinstance(func, ast.Attribute)
        and isinstance(func.value, ast.Name)
        and func.value.id in ("np", "numpy")
    )


# ------------------------------------------------------------ suppression


def parse_suppressions(
    source: str,
) -> tuple[dict[int, dict[str, str]], set[int], list[tuple[int, str]]]:
    """→ ({lineno: {code: reason}}, {standalone-comment linenos},
    [(lineno, code) with empty reason]).

    Only real COMMENT tokens count (the tokenize module, not a line
    regex), so the disable syntax can be quoted in docstrings and
    strings without acting as a suppression.  ``standalone`` marks
    comment-only lines: a disable also covers the NEXT line only when
    it stands alone — a trailing disable must not waive the line below.
    """
    by_line: dict[int, dict[str, str]] = {}
    standalone: set[int] = set()
    missing: list[tuple[int, str]] = []
    lines = source.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _DISABLE_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        line_text = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        if line_text.lstrip().startswith("#"):
            standalone.add(lineno)
        entries = by_line.setdefault(lineno, {})
        for code, reason in _ENTRY_RE.findall(m.group("body")):
            if reason and reason.strip():
                entries[code] = reason.strip()
            else:
                missing.append((lineno, code))
    return by_line, standalone, missing


# ------------------------------------------------------- pass A: jit index


class JitIndex:
    """Which function/lambda nodes are jitted, and their static params."""

    def __init__(self) -> None:
        self.defs: dict[_FuncNode, frozenset[str]] = {}
        self.lambdas: dict[ast.Lambda, frozenset[str]] = {}
        self.call_sites: list[tuple[ast.Call, Optional[str], bool]] = []

    def statics_for(self, node) -> frozenset[str]:  # noqa: ANN001
        if isinstance(node, ast.Lambda):
            return self.lambdas.get(node, frozenset())
        return self.defs.get(node, frozenset())


def _statics_from_keywords(
    call: ast.Call, target: Optional[_FuncNode]
) -> frozenset[str]:
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums" and target is not None:
            params = _positional_params(target)
            for i in _const_ints(kw.value):
                if 0 <= i < len(params):
                    names.add(params[i])
    return frozenset(names)


def _index_module(
    tree: ast.Module, rel_path: str
) -> tuple[JitIndex, dict[_FuncNode, str]]:
    index = JitIndex()

    # qualnames + name→def map (bare-name resolution is enough here:
    # jitted locals like decode_steps are unique within their module)
    qualnames: dict[_FuncNode, str] = {}
    by_name: dict[str, list[_FuncNode]] = {}

    def fill(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                qualnames[child] = qual
                by_name.setdefault(child.name, []).append(child)
                fill(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                fill(child, f"{prefix}{child.name}.")
            else:
                fill(child, prefix)

    fill(tree, "")

    # registry entries (methods jitted from another module)
    registered = config.registry_qualnames(rel_path)
    for node, qual in qualnames.items():
        if qual in registered:
            index.defs[node] = config.REGISTRY_STATIC_PARAMS

    # decorators
    for node in qualnames:
        for dec in node.decorator_list:
            if _is_jit_expr(dec) or _is_shard_map_expr(dec):
                index.defs.setdefault(node, frozenset())
            elif isinstance(dec, ast.Call) and (
                _is_jit_expr(dec.func)
                or (
                    _is_partial_expr(dec.func)
                    and dec.args
                    and _is_jit_expr(dec.args[0])
                )
            ):
                index.defs[node] = index.defs.get(
                    node, frozenset()
                ) | _statics_from_keywords(dec, node)

    # call sites: jax.jit(target, ...) / shard_map(target, ...)
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        if not (_is_jit_expr(call.func) or _is_shard_map_expr(call.func)):
            continue
        if not call.args:
            continue
        target = call.args[0]
        bound_static: set[str] = set()
        name: Optional[str] = None
        resolved: list[_FuncNode] = []
        if isinstance(target, ast.Call) and _is_partial_expr(target.func):
            # functools.partial(fn, a, b, kw=...): bound args are static
            inner = target.args[0] if target.args else None
            if isinstance(inner, ast.Name):
                name = inner.id
                resolved = by_name.get(name, [])
            elif isinstance(inner, ast.Attribute):
                name = inner.attr
            bound_static.update(
                kw.arg for kw in target.keywords if kw.arg is not None
            )
            n_bound = max(len(target.args) - 1, 0)
            for fn in resolved:
                bound_static.update(_positional_params(fn)[:n_bound])
        elif isinstance(target, ast.Name):
            name = target.id
            resolved = by_name.get(name, [])
        elif isinstance(target, ast.Attribute):
            name = target.attr
        elif isinstance(target, ast.Lambda):
            statics = _statics_from_keywords(call, None)
            index.lambdas[target] = (
                index.lambdas.get(target, frozenset()) | statics
            )
        for fn in resolved:
            statics = _statics_from_keywords(call, fn) | bound_static
            index.defs[fn] = index.defs.get(fn, frozenset()) | frozenset(
                statics
            )
        zero_arg_lambda = (
            isinstance(target, ast.Lambda)
            and not _positional_params_of_lambda(target)
        )
        index.call_sites.append((call, name, zero_arg_lambda))

    return index, qualnames


def _positional_params_of_lambda(lam: ast.Lambda) -> list[str]:
    a = lam.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


# ------------------------------------------------------- pass B: checker


class _Checker(ast.NodeVisitor):
    def __init__(
        self,
        rel_path: str,
        index: JitIndex,
        findings: list[Finding],
        awaited: Optional[set] = None,
    ) -> None:
        self.rel_path = rel_path
        self.index = index
        self.findings = findings
        self.step_loop = config.is_step_loop_module(rel_path)
        # (kind, traced-params, static-params); nested defs inside a
        # jitted function inherit its frame — they are traced too
        self._frames: list[tuple[str, frozenset[str], frozenset[str]]] = []
        # awaited calls are async-native, not event-loop blockers
        self._awaited: set = awaited or set()
        self._raise_depth = 0

    # ----- frame helpers

    def _push(self, node, kind: str) -> None:  # noqa: ANN001
        jitted = (
            node in self.index.defs
            if not isinstance(node, ast.Lambda)
            else node in self.index.lambdas
        )
        if jitted:
            params = frozenset(
                _all_params(node)
                if not isinstance(node, ast.Lambda)
                else _positional_params_of_lambda(node)
            )
            statics = self.index.statics_for(node)
            self._frames.append((kind, params, statics))
        elif self._frames and self._frames[-1][1]:
            # keep the enclosing jit context, switch the function kind
            self._frames.append((kind, *self._frames[-1][1:]))
        else:
            self._frames.append((kind, frozenset(), frozenset()))

    @property
    def _in_jit(self) -> bool:
        return bool(self._frames) and bool(self._frames[-1][1])

    @property
    def _in_async(self) -> bool:
        return bool(self._frames) and self._frames[-1][0] == "async"

    def _emit(self, node: ast.AST, code: str, detail: str = "") -> None:
        message = config.RULES[code].split(" (")[0]
        if detail:
            message = f"{message}: {detail}"
        self.findings.append(
            Finding(
                path=self.rel_path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0),
                code=code,
                message=message,
            )
        )

    # ----- scope tracking

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_jit_decl(node)
        self._push(node, "sync")
        self.generic_visit(node)
        self._frames.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_jit_decl(node)
        self._push(node, "async")
        self.generic_visit(node)
        self._frames.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._push(node, "lambda")
        self.generic_visit(node)
        self._frames.pop()

    # ----- TPL103: static coverage at the jit declaration

    def _check_jit_decl(self, node: _FuncNode) -> None:
        statics = self.index.defs.get(node)
        if statics is None:
            return
        for arg in (*node.args.posonlyargs, *node.args.args,
                    *node.args.kwonlyargs):
            if arg.arg in statics or arg.arg == "self":
                continue
            ann = arg.annotation
            if (
                isinstance(ann, ast.Name)
                and ann.id in ("int", "bool")
            ):
                self._emit(
                    node, "TPL103",
                    f"parameter {arg.arg!r} of jitted {node.name!r}",
                )

    # ----- TPL101: traced-value branching

    def _check_test(self, stmt: ast.AST, test: ast.expr) -> None:
        if not self._in_jit:
            return
        _, params, statics = self._frames[-1]
        traced = params - statics
        for comp in ast.walk(test):
            if not isinstance(comp, ast.Compare):
                continue
            if all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in comp.ops
            ):
                continue  # `x is None` / `"k" in layer` are trace-static
            for side in (comp.left, *comp.comparators):
                hit = _mentions_shape(side) or any(
                    isinstance(sub, ast.Name) and sub.id in traced
                    for sub in ast.walk(side)
                )
                if hit:
                    self._emit(stmt, "TPL101")
                    return

    def visit_If(self, node: ast.If) -> None:
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_test(node, node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_test(node, node.test)
        self.generic_visit(node)

    # ----- TPL102: shape-keyed strings / dict keys

    def visit_Raise(self, node: ast.Raise) -> None:
        # shape-formatted *error messages* are trace-time validation,
        # not shape-keyed control flow — exempt
        self._raise_depth += 1
        self.generic_visit(node)
        self._raise_depth -= 1

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if self._in_jit and not self._raise_depth and _mentions_shape(node):
            self._emit(node, "TPL102")
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        if self._in_jit and any(
            key is not None and _mentions_shape(key) for key in node.keys
        ):
            self._emit(node, "TPL102")
        self.generic_visit(node)

    # ----- call-shaped rules

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        name = _call_name(func)

        if self.step_loop:
            # TPL201: explicit syncs
            if isinstance(func, ast.Attribute) and (
                name in config.SYNC_ATTR_CALLS or name == "device_get"
            ):
                self._emit(node, "TPL201", f"{name}()")
            # TPL202: device→host pulls on hint-named values
            elif (
                _np_rooted(func)
                and name in config.HOST_PULLS
                and node.args
                and _device_hinted(node.args[0])
            ):
                self._emit(node, "TPL202", f"np.{name}(...)")
            elif (
                isinstance(func, ast.Name)
                and name in config.HOST_CASTS
                and len(node.args) == 1
                and _device_hinted(node.args[0])
            ):
                self._emit(node, "TPL202", f"{name}(...)")

        if self._in_async:
            # TPL304: wait_for over an Event.wait() — the bpo-42130
            # already-set-event pattern (py3.10 swallows the timeout
            # cancellation, so the wait can hang past its deadline)
            if name == "wait_for" and node.args:
                inner = node.args[0]
                if (
                    isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "wait"
                    and not inner.args
                ):
                    recv = _call_name(inner.func.value) or "event"
                    self._emit(
                        node, "TPL304",
                        f"wait_for({recv}.wait(), ...)",
                    )
            if (
                isinstance(func, ast.Attribute)
                and name == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id in config.SLEEP_MODULES
            ):
                self._emit(node, "TPL301")
            elif (
                (
                    isinstance(func, ast.Name)
                    and name in config.SYNC_IO_NAMES
                )
                or (
                    isinstance(func, ast.Attribute)
                    and name in config.SYNC_IO_ATTRS
                )
            ) and node not in self._awaited:
                # awaited calls are async-native (aiopath-style APIs
                # share these method names)
                self._emit(node, "TPL302", f"{name}(...)")
            elif (
                name in config.BLOCKING_HELPERS
                and node not in self._awaited
            ):
                self._emit(node, "TPL303", f"{name}(...)")

        self.generic_visit(node)


def _check_jit_call_sites(index: JitIndex, rel_path: str,
                          findings: list[Finding]) -> None:
    """TPL104 at runtime-wrapped jit entry points: large-buffer names
    must carry donate_argnums (decorated kernel jits are read-only by
    convention here and exempt)."""
    for call, name, zero_arg_lambda in index.call_sites:
        if _is_shard_map_expr(call.func):
            continue
        if zero_arg_lambda or name is None:
            continue
        if not config.LARGE_BUFFER.search(name):
            continue
        if any(kw.arg == "donate_argnums" for kw in call.keywords):
            continue
        findings.append(
            Finding(
                path=rel_path,
                line=call.lineno,
                col=call.col_offset,
                code="TPL104",
                message=f"{config.RULES['TPL104'].split(' (')[0]}: "
                        f"jax.jit({name}, ...)",
            )
        )


# ------------------------------------------------------------- public API


class ModuleAnalysis:
    """One module's findings plus the artifacts the project-wide passes
    (cross-module lock cycles, manifest staleness) consume."""

    def __init__(
        self,
        findings: list[Finding],
        lock_graph,  # noqa: ANN001 — concurrency.ModuleLockGraph
        lattice_sites: list[dict],
        suppressions: dict[int, dict[str, str]],
        standalone: set[int],
    ):
        self.findings = findings
        self.lock_graph = lock_graph
        self.lattice_sites = lattice_sites
        self.suppressions = suppressions
        self.standalone = standalone


def _apply_suppressions(
    findings: list[Finding],
    suppressions: dict[int, dict[str, str]],
    standalone: set[int],
) -> None:
    for f in findings:
        if f.code == "TPL000":
            continue  # the audit rule itself cannot be waived
        # own line (trailing comment), or a STANDALONE disable directly
        # above — a trailing disable never waives the line below it
        reason = suppressions.get(f.line, {}).get(f.code)
        if reason is None and f.line - 1 in standalone:
            reason = suppressions.get(f.line - 1, {}).get(f.code)
        if reason is not None:
            f.suppressed = True
            f.reason = reason


def analyze_module(
    source: str, rel_path: str, manifest: Optional[dict] = None
) -> ModuleAnalysis:
    """Full per-module analysis (every per-file rule family)."""
    tree = ast.parse(source, filename=rel_path)
    index, _ = _index_module(tree, rel_path)

    findings: list[Finding] = []
    suppressions, standalone, missing_reasons = parse_suppressions(source)
    for lineno, code in missing_reasons:
        findings.append(
            Finding(
                path=rel_path,
                line=lineno,
                col=0,
                code="TPL000",
                message=f"{config.RULES['TPL000'].split(': #')[0]} "
                        f"(disable={code})",
            )
        )

    def emit(node, code, detail="") -> None:  # noqa: ANN001
        message = config.RULES[code].split(" (")[0]
        if detail:
            message = f"{message}: {detail}"
        findings.append(
            Finding(
                path=rel_path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0),
                code=code,
                message=message,
            )
        )

    _check_jit_call_sites(index, rel_path, findings)
    awaited = {n.value for n in ast.walk(tree) if isinstance(n, ast.Await)}
    _Checker(rel_path, index, findings, awaited).visit(tree)

    # TPL4xx: lock discipline (+ the module's own lock-order cycles;
    # cross-module cycles are the CLI's project-wide pass)
    lock_graph = concurrency.analyze_module(tree, rel_path, emit)
    concurrency.emit_cycles(
        lock_graph.edges(),
        lambda _path, line, code, detail: emit(
            astutil.Anchor(line), code, detail
        ),
    )
    # TPL5xx: resource pairing + raw task spawns + lifecycle grammar
    resources.check_pairing(tree, rel_path, emit)
    resources.check_task_spawns(tree, rel_path, emit)
    lifecycle.check_module(tree, rel_path, emit)
    # TPL6xx: compile-lattice manifest agreement (per-file half)
    lattice_sites = lattice.check_module(
        tree, rel_path, emit, manifest=manifest
    )

    _apply_suppressions(findings, suppressions, standalone)
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return ModuleAnalysis(
        findings, lock_graph, lattice_sites, suppressions, standalone
    )


def analyze_source(
    source: str, rel_path: str, manifest: Optional[dict] = None
) -> list[Finding]:
    """All findings for one module (suppressed ones flagged, not
    dropped, so callers can audit the suppression inventory).
    ``manifest`` overrides the checked-in compile-lattice manifest —
    unit fixtures pin their own so they never couple to the live jit
    lattice."""
    return analyze_module(source, rel_path, manifest=manifest).findings


def analyze_file(path, root=None, manifest=None) -> list[Finding]:  # noqa: ANN001
    p = Path(path)
    rel = p.relative_to(root).as_posix() if root else p.as_posix()
    return analyze_source(
        p.read_text(encoding="utf-8"), rel, manifest=manifest
    )


def analyze_project(
    files, root=None, manifest=None, attention_doc=None
) -> list[Finding]:  # noqa: ANN001
    """Per-file analysis over ``files`` PLUS the project-wide passes:
    cross-module lock-order cycles (TPL402) and manifest staleness /
    doc drift (TPL602/TPL603).  The CLI's full-package invocation."""
    if manifest is None:
        manifest = config.load_manifest()
    analyses: dict[str, ModuleAnalysis] = {}
    findings: list[Finding] = []
    for path in files:
        p = Path(path)
        rel = p.relative_to(root).as_posix() if root else p.as_posix()
        analysis = analyze_module(
            p.read_text(encoding="utf-8"), rel, manifest=manifest
        )
        analyses[rel] = analysis
        findings.extend(analysis.findings)

    # cross-module lock-order cycles: the project edge set additionally
    # resolves calls ACROSS modules.  Dedup against the cycles the
    # per-file passes ACTUALLY reported — not by which module the
    # edges attribute to: a cycle whose edges all sit in one module can
    # still be invisible per-file when its call targets live elsewhere
    per_file_cycles: set[tuple[str, ...]] = set()
    for analysis in analyses.values():
        for cycle, _p, _l in concurrency.find_cycles(
            analysis.lock_graph.edges()
        ):
            per_file_cycles.add(concurrency.canonical_cycle(cycle))
    merged = concurrency.project_edges(
        [a.lock_graph for a in analyses.values()]
    )
    cross: list[Finding] = []
    for cycle, path_, line in concurrency.find_cycles(merged):
        if concurrency.canonical_cycle(cycle) in per_file_cycles:
            continue  # already reported by the per-file pass
        pretty = " -> ".join([*cycle, cycle[0]])
        cross.append(
            Finding(
                path=path_, line=line, col=0, code="TPL402",
                message=f"{config.RULES['TPL402'].split(' (')[0]}: "
                        f"{pretty} (cross-module)",
            )
        )
    for f in cross:
        analysis = analyses.get(f.path)
        if analysis is not None:
            _apply_suppressions(
                [f], analysis.suppressions, analysis.standalone
            )
    findings.extend(cross)

    # manifest staleness + docs drift
    def emit_at(path_, line, code, detail) -> None:  # noqa: ANN001
        message = config.RULES[code].split(" (")[0]
        findings.append(
            Finding(
                path=str(path_), line=line, col=0, code=code,
                message=f"{message}: {detail}",
            )
        )

    lattice.check_project(
        {rel: a.lattice_sites for rel, a in analyses.items()},
        emit_at, manifest=manifest, attention_doc=attention_doc,
    )
    return findings
