"""tpulint: AST-based hazard analyzer for this JAX/TPU serving stack.

Six hazard families, one per bug class we have paged on:

* **TPL1xx recompile hazards** — code inside a jitted function that makes
  the traced program shape- or value-dependent (each novel shape is a
  20-40s XLA/Mosaic compile on TPU; see compile_tracker.py).
* **TPL2xx host-sync hazards** — device→host pulls on the engine step
  path (``engine/core.py → runner.py → pipeline.py → ops/*``), where a
  single stray ``.item()`` serialises the async dispatch pipeline.
* **TPL3xx async-blocking hazards** — synchronous work on the event loop
  in the serving tier (``grpc/``, ``http.py``, ``engine/async_llm.py``),
  which stalls every in-flight stream at once.
* **TPL4xx lock discipline** (tools/tpulint/concurrency.py) —
  interprocedural lock-acquisition graphs over ``engine/``,
  ``supervisor/`` and ``frontdoor/``: awaits under engine locks,
  cross-module lock-order cycles, loop/worker-thread write races.
* **TPL5xx resource pairing** (tools/tpulint/resources.py) —
  acquire/release pairs (pins, arena charges, pages, epochs, failpoint
  arms) must release on every exit path; raw ``asyncio.create_task``
  must ride ``utils.spawn_task``'s strong-ref set.
* **TPL6xx compile-lattice manifest** (tools/tpulint/lattice.py) —
  every ``track_jit`` entry point with its static args is pinned in
  the checked-in ``lattice_manifest.json`` (``--write-lattice``
  regenerates); unmanifested/stale/undocumented entries fail the gate.

The runtime companion is ``engine/sanitizer.py`` (TGIS_TPU_SANITIZE=1):
step-boundary invariant checks over the accounting these rules guard.

The analyzer knows which functions are jitted: direct ``jax.jit`` /
``shard_map`` decoration, ``functools.partial(jax.jit, ...)``, call-site
``jax.jit(fn)`` wrapping (including the entry points compile_tracker's
``track_jit`` registers), plus a per-file registry for model methods that
are jitted from another module (tools/tpulint/config.py JIT_REGISTRY).

Findings are suppressed line-local with a mandatory reason::

    np.asarray(packed_dev)  # tpulint: disable=TPL202(one sanctioned fetch per wave)

A reason-less ``disable`` is itself an error (TPL000), so the gate
enforces that every suppression is explained.  CLI: ``python -m
tools.tpulint vllm_tgis_adapter_tpu`` or ``nox -s tpulint``; exit codes
are scriptable (0 clean, 1 findings, 2 internal error) like
tools/obs_check.py.  See docs/STATIC_ANALYSIS.md for the full rule table.
"""

from tools.tpulint.analyzer import Finding, analyze_file, analyze_source

__all__ = ["Finding", "analyze_file", "analyze_source"]
