"""TPL4xx: interprocedural lock-discipline analysis.

Three rules over the engine's locking idiom (``async with self._lock`` /
``with _lock`` on attribute- or module-resolved locks):

* **TPL401** — an ``await`` of anything but ``asyncio.to_thread`` while
  holding an engine lock.  The replica lock, the tier transfer lock and
  the adapter stream lock all serialize the step loop's host phases; an
  arbitrary suspension under one extends the critical section by an
  unbounded amount and is the precondition for every lock-order deadlock.
* **TPL402** — lock-order cycles.  Each module contributes a directed
  graph (lock A held while lock B is acquired, directly or through a
  called function's own acquisitions — the interprocedural part); a
  cycle in the merged graph means two tasks can each hold one half.
* **TPL403** — a ``self.<attr>`` written both from coroutine context
  (an ``async def`` body) and from worker-thread context (a function
  dispatched via ``asyncio.to_thread``, or a same-class function it
  calls) with no common lock guarding both writes — the torn-accounting
  bug class of the transfer paths.

Lock identity is resolved statically: ``self.X`` → ``Class.X``,
``other.X`` → ``*.X`` (instance wildcard — two replicas' ``rep.lock``
are deliberately the SAME node, because taking two instances of one
lock class in opposite orders is exactly the hazard), bare names →
``module:name``.
"""

from __future__ import annotations

import ast
from typing import Optional, Union

from tools.tpulint import config
from tools.tpulint.astutil import Anchor, call_bare_name

_FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]


class FunctionLockInfo:
    """Lock behavior of one function, for the cross-function passes."""

    def __init__(self, qualname: str, node: _FuncNode, is_async: bool):
        self.qualname = qualname
        self.node = node
        self.is_async = is_async
        #: every lock this function acquires directly: (lock_id, lineno)
        self.acquired: list[tuple[str, int]] = []
        #: (outer_lock, inner_lock, lineno) — direct nesting in this fn
        self.nested: list[tuple[str, str, int]] = []
        #: (held_lock, callee_name, bare, lineno) — calls under a lock;
        #: ``bare`` distinguishes ``release(x)`` (resolves to module
        #: functions / nested defs) from ``obj.release(x)`` (resolves to
        #: methods only — a semaphore's ``.release`` must never alias a
        #: module-level function of the same name)
        self.calls_under_lock: list[tuple[str, str, bool, int]] = []
        #: (name, bare) of everything this function calls (any context)
        self.calls: set[tuple[str, bool]] = set()


class ModuleLockGraph:
    """Per-module result: function infos + the module's own lock edges
    (the CLI merges these across modules for the global cycle pass)."""

    def __init__(self, rel_path: str):
        self.rel_path = rel_path
        self.functions: dict[str, FunctionLockInfo] = {}
        #: name -> [qualnames] for bare-name call resolution
        self.by_name: dict[str, list[str]] = {}

    def resolve(self, caller: str, name: str, bare: bool) -> list[str]:
        """Qualnames a call from ``caller`` may reach: bare-name calls
        resolve to module-level functions and defs nested under the
        caller; attribute calls resolve to class methods / nested defs
        (never module-level functions — ``sem.release()`` must not
        alias a module ``release``)."""
        out = []
        for qual in self.by_name.get(name, ()):
            nested_in_caller = qual.startswith(f"{caller}.")
            if bare and ("." not in qual or nested_in_caller):
                out.append(qual)
            elif not bare and ("." in qual):
                out.append(qual)
        return out

    def edges(self) -> list[tuple[str, str, str, int]]:
        """(outer, inner, path, line) lock-order edges, interprocedural
        within this module's call graph."""
        closure = _lock_closures(dict(self.functions), self.resolve)
        out: list[tuple[str, str, str, int]] = []
        for qual, info in self.functions.items():
            for outer, inner, line in info.nested:
                out.append((outer, inner, self.rel_path, line))
            for held, callee, bare, line in info.calls_under_lock:
                for target in self.resolve(qual, callee, bare):
                    for inner in closure.get(target, ()):
                        out.append((held, inner, self.rel_path, line))
        return out


def resolve_lock(expr: ast.expr, class_name: Optional[str],
                 rel_path: str) -> Optional[str]:
    """Static lock identity of a with-item context expression, or None
    when the expression does not look like a lock at all."""
    target = expr
    # unwrap `lock.acquire()`-style calls conservatively: the with form
    # is the idiom here, so only bare names/attributes are resolved
    if isinstance(target, ast.Attribute):
        if not config.LOCK_NAME.search(target.attr):
            return None
        base = target.value
        if isinstance(base, ast.Name) and base.id == "self":
            return f"{class_name or '?'}.{target.attr}"
        return f"*.{target.attr}"
    if isinstance(target, ast.Name):
        if not config.LOCK_NAME.search(target.id):
            return None
        return f"{rel_path}:{target.id}"
    return None


def _allowed_await(value: ast.expr) -> bool:
    """Is this awaitee sanctioned under a held lock (TPL401)?"""
    if isinstance(value, ast.Call):
        name = call_bare_name(value.func)
        return name in config.ALLOWED_AWAITS_UNDER_LOCK
    return False


class _LockVisitor(ast.NodeVisitor):
    """One walk collecting lock info + TPL401 findings for a module."""

    def __init__(self, rel_path: str, emit) -> None:  # noqa: ANN001
        self.rel_path = rel_path
        self.emit = emit  # emit(node, code, detail)
        self.graph = ModuleLockGraph(rel_path)
        self._class: Optional[str] = None
        self._fn: Optional[FunctionLockInfo] = None
        self._held: list[str] = []  # lock stack within current function
        self._prefix = ""

    # ------------------------------------------------------------- scopes

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, prev_prefix = self._class, self._prefix
        self._class = node.name
        self._prefix = f"{prev_prefix}{node.name}."
        self.generic_visit(node)
        self._class, self._prefix = prev, prev_prefix

    def _visit_fn(self, node: _FuncNode, is_async: bool) -> None:
        qual = f"{self._prefix}{node.name}"
        info = FunctionLockInfo(qual, node, is_async)
        prev_fn, prev_held, prev_prefix = self._fn, self._held, self._prefix
        self._fn, self._held, self._prefix = info, [], f"{qual}."
        self.graph.functions[qual] = info
        self.graph.by_name.setdefault(node.name, []).append(qual)
        self.generic_visit(node)
        self._fn, self._held, self._prefix = prev_fn, prev_held, prev_prefix

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_fn(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_fn(node, is_async=True)

    # -------------------------------------------------------------- locks

    def _enter_with(self, node, is_async: bool) -> None:  # noqa: ANN001
        # push each item onto the held stack BEFORE resolving the next:
        # `with a_lock, b_lock:` acquires in item order and must emit
        # the a->b ordering edge exactly like two nested statements
        pushed = 0
        for item in node.items:
            lock = resolve_lock(item.context_expr, self._class,
                                self.rel_path)
            if lock is None:
                continue
            if self._fn is not None:
                self._fn.acquired.append((lock, node.lineno))
                if self._held:
                    self._fn.nested.append(
                        (self._held[-1], lock, node.lineno)
                    )
            self._held.append(lock)
            pushed += 1
        self.generic_visit(node)
        del self._held[len(self._held) - pushed:]

    def visit_With(self, node: ast.With) -> None:
        self._enter_with(node, is_async=False)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._enter_with(node, is_async=True)

    # ------------------------------------------------- awaits and calls

    def visit_Await(self, node: ast.Await) -> None:
        if (
            self._held
            and config.is_lock_scope_module(self.rel_path)
            and not _allowed_await(node.value)
        ):
            self.emit(
                node, "TPL401",
                f"holding {self._held[-1]}",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = call_bare_name(node.func)
        if name is not None and self._fn is not None:
            bare = isinstance(node.func, ast.Name)
            self._fn.calls.add((name, bare))
            if self._held:
                self._fn.calls_under_lock.append(
                    (self._held[-1], name, bare, node.lineno)
                )
        self.generic_visit(node)


def _lock_closures(functions: dict, resolve) -> dict:  # noqa: ANN001
    """Transitive lock closure per function key — fixpoint iteration,
    so call CYCLES converge to the full set instead of caching a
    partial expansion (lock sets only grow, so termination is
    guaranteed)."""
    closure = {
        key: {lock for lock, _ in info.acquired}
        for key, info in functions.items()
    }
    callees = {
        key: [
            target
            for name, bare in info.calls
            for target in resolve(key, name, bare)
        ]
        for key, info in functions.items()
    }
    changed = True
    while changed:
        changed = False
        for key, targets in callees.items():
            acc = closure[key]
            before = len(acc)
            for target in targets:
                acc |= closure.get(target, set())
            if len(acc) != before:
                changed = True
    return closure


def project_edges(
    graphs: list[ModuleLockGraph],
) -> list[tuple[str, str, str, int]]:
    """Lock-order edges over a WHOLE analyzed file set, resolving calls
    across modules (imported module-level functions by bare name, class
    methods by attribute name).  Edge paths are attributed to the
    calling module."""
    by_name: dict[str, list[tuple[str, str]]] = {}
    functions: dict[tuple[str, str], FunctionLockInfo] = {}
    for g in graphs:
        for qual, info in g.functions.items():
            functions[(g.rel_path, qual)] = info
            by_name.setdefault(
                qual.rsplit(".", 1)[-1], []
            ).append((g.rel_path, qual))

    def resolve(caller: tuple[str, str], name: str,
                bare: bool) -> list[tuple[str, str]]:
        caller_path, caller_qual = caller
        out = []
        for path, qual in by_name.get(name, ()):
            nested = (
                path == caller_path
                and qual.startswith(f"{caller_qual}.")
            )
            if bare and ("." not in qual or nested):
                out.append((path, qual))
            elif not bare and "." in qual:
                out.append((path, qual))
        return out

    closure = _lock_closures(functions, resolve)

    out: list[tuple[str, str, str, int]] = []
    for key, info in functions.items():
        path = key[0]
        for outer, inner, line in info.nested:
            out.append((outer, inner, path, line))
        for held, callee, bare, line in info.calls_under_lock:
            for target in resolve(key, callee, bare):
                for inner in closure.get(target, ()):
                    out.append((held, inner, path, line))
    return out


def canonical_cycle(cycle: list[str]) -> tuple[str, ...]:
    """Rotation-canonical form of a lock cycle (for cross-pass dedup)."""
    i = cycle.index(min(cycle))
    return tuple(cycle[i:] + cycle[:i])


def find_cycles(
    edges: list[tuple[str, str, str, int]],
) -> list[tuple[list[str], str, int]]:
    """Cycles in the lock-order graph → ``(lock_cycle, path, line)``,
    one per distinct cycle (canonicalized by rotation), anchored at the
    smallest contributing edge site."""
    adj: dict[str, dict[str, tuple[str, int]]] = {}
    for outer, inner, path, line in edges:
        slot = adj.setdefault(outer, {})
        if inner not in slot or (path, line) < slot[inner]:
            slot[inner] = (path, line)

    seen: set[tuple[str, ...]] = set()
    out: list[tuple[list[str], str, int]] = []

    canonical = canonical_cycle

    def dfs(start: str, node: str, path: list[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cycle = path[:]
                key = canonical(cycle)
                if key not in seen:
                    seen.add(key)
                    sites = [
                        adj[cycle[i]][cycle[(i + 1) % len(cycle)]]
                        for i in range(len(cycle))
                    ]
                    anchor = min(sites)
                    out.append((cycle, anchor[0], anchor[1]))
            elif nxt not in path and len(path) < 8:
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return out


# ----------------------------------------------------------------- TPL403


def _attr_writes(fn: _FuncNode) -> list[tuple[str, int, frozenset]]:
    """``self.<attr>`` writes in ``fn``'s own body → (attr, lineno,
    locks-held) with the with-stack of enclosing lock contexts."""
    out: list[tuple[str, int, frozenset]] = []

    def walk(stmts, held: frozenset) -> None:  # noqa: ANN001
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            now = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = resolve_lock(item.context_expr, None, "")
                    if lock is not None:
                        now = now | {lock.rsplit(".", 1)[-1]}
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.append((t.attr, node.lineno, now))
            walk(list(ast.iter_child_nodes(node)), now)

    walk(list(fn.body), frozenset())
    return out


def check_shared_writes(tree: ast.Module, rel_path: str, emit) -> None:  # noqa: ANN001
    """TPL403 over one module's classes."""
    if not config.is_lock_scope_module(rel_path):
        return

    # names dispatched to worker threads anywhere in the module
    thread_roots: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_bare_name(node.func)
        if name == "to_thread" and node.args:
            root = call_bare_name(node.args[0])
            if root:
                thread_roots.add(root)
        elif name == "run_in_executor" and len(node.args) >= 2:
            root = call_bare_name(node.args[1])
            if root:
                thread_roots.add(root)

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        methods: dict[str, _FuncNode] = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        calls: dict[str, set[str]] = {
            name: {
                call_bare_name(c.func)
                for c in ast.walk(m) if isinstance(c, ast.Call)
            } - {None}
            for name, m in methods.items()
        }
        # worker-thread context: to_thread roots + same-class closure
        thread_ctx: set[str] = set()
        frontier = [n for n in methods if n in thread_roots]
        while frontier:
            name = frontier.pop()
            if name in thread_ctx:
                continue
            thread_ctx.add(name)
            frontier.extend(
                c for c in calls.get(name, ()) if c in methods
            )

        coroutine_writes: dict[str, list[tuple[int, frozenset]]] = {}
        thread_writes: dict[str, list[tuple[int, frozenset]]] = {}
        for name, m in methods.items():
            is_async = isinstance(m, ast.AsyncFunctionDef)
            in_thread = name in thread_ctx and not is_async
            if not is_async and not in_thread:
                continue
            for attr, line, held in _attr_writes(m):
                side = coroutine_writes if is_async else thread_writes
                side.setdefault(attr, []).append((line, held))

        for attr in sorted(set(coroutine_writes) & set(thread_writes)):
            for t_line, t_held in thread_writes[attr]:
                # a common lock must guard BOTH sides; the thread side
                # can only hold sync locks, so compare bare attr names
                guarded = any(
                    t_held & c_held
                    for _line, c_held in coroutine_writes[attr]
                )
                if not guarded:
                    emit_line = t_line
                    emit(
                        Anchor(emit_line), "TPL403",
                        f"self.{attr} written in worker-thread context "
                        f"here and in coroutine context at line "
                        f"{coroutine_writes[attr][0][0]} "
                        f"({cls.name})",
                    )
                    break  # one finding per attribute per class


def analyze_module(
    tree: ast.Module, rel_path: str, emit
) -> ModuleLockGraph:  # noqa: ANN001
    """Run the TPL4xx per-module passes; returns the module's lock graph
    for the caller's (per-file or project-wide) cycle detection."""
    visitor = _LockVisitor(rel_path, emit)
    visitor.visit(tree)
    check_shared_writes(tree, rel_path, emit)
    return visitor.graph


def emit_cycles(
    edges: list[tuple[str, str, str, int]], emit_at
) -> None:  # noqa: ANN001
    """TPL402 over a merged edge list.  ``emit_at(path, line, code,
    detail)`` so the CLI can attribute cross-module cycles to the right
    file."""
    for cycle, path, line in find_cycles(edges):
        pretty = " -> ".join([*cycle, cycle[0]])
        emit_at(path, line, "TPL402", pretty)
