"""TPL6xx: the compile-lattice manifest.

Every jitted entry point in this codebase goes through
``compile_tracker.track_jit(name, jax.jit(fn, ...))`` — that is the
complete compile lattice (docs/ATTENTION.md carries the expected compile
counts per entry).  This pass statically enumerates those sites with
their compile-shape-relevant parameters (``static_argnums`` /
``static_argnames`` / ``functools.partial``-bound arguments / donation)
and diffs them against the checked-in
``tools/tpulint/lattice_manifest.json``:

* **TPL601** (per-file) — a ``track_jit`` site absent from, or
  disagreeing with, its manifest entry.  Adding a jit entry point or a
  new static argument without updating the manifest (and the
  docs/ATTENTION.md counts) is a lint failure, not a silent lattice
  growth discovered as a 20-40 s serving stall.
* **TPL602** (project-wide) — a manifest entry with no matching site in
  the analyzed module (stale after a deletion/rename).
* **TPL603** (project-wide) — a manifest entry name missing from
  docs/ATTENTION.md.

Entry names built with f-strings (the pipeline's ``f"pp{s}_prefill"``)
are normalized to ``fnmatch`` patterns (``pp*_prefill``); the live-boot
test matches the compile tracker's observed entry names against the
same patterns.  Regenerate after an intentional change with
``python -m tools.tpulint --write-lattice``.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Optional

from tools.tpulint import config
from tools.tpulint.astutil import Anchor, call_bare_name


def _name_pattern(node: ast.expr) -> Optional[str]:
    """track_jit's name argument as a literal or fnmatch pattern."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _const_ints(node: Optional[ast.expr]) -> list[int]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return sorted(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        )
    return []


def _const_strs(node: Optional[ast.expr]) -> list[str]:
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return sorted(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return []


def _is_partial(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute):
        return func.attr == "partial"
    return isinstance(func, ast.Name) and func.id == "partial"


def _is_jit(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute):
        return func.attr == "jit"
    return isinstance(func, ast.Name) and func.id == "jit"


def describe_site(call: ast.Call, module: str) -> Optional[dict]:
    """One ``track_jit(name, jax.jit(...), ...)`` call → manifest entry
    dict, or None when the call is not a recognizable track_jit site."""
    if call_bare_name(call.func) != "track_jit" or len(call.args) < 2:
        return None
    name = _name_pattern(call.args[0])
    if name is None:
        return None
    entry = {
        "module": module,
        "name": name,
        "static_argnums": [],
        "static_argnames": [],
        "partial_kwargs": [],
        "partial_pos": 0,
        "donate": False,
        "line": call.lineno,
    }
    jit_call = call.args[1]
    if isinstance(jit_call, ast.Call) and _is_jit(jit_call.func):
        for kw in jit_call.keywords:
            if kw.arg == "static_argnums":
                entry["static_argnums"] = _const_ints(kw.value)
            elif kw.arg == "static_argnames":
                entry["static_argnames"] = _const_strs(kw.value)
            elif kw.arg == "donate_argnums":
                entry["donate"] = True
        target = jit_call.args[0] if jit_call.args else None
        if isinstance(target, ast.Call) and _is_partial(target.func):
            entry["partial_kwargs"] = sorted(
                kw.arg for kw in target.keywords if kw.arg is not None
            )
            entry["partial_pos"] = max(0, len(target.args) - 1)
    return entry


def iter_sites(tree: ast.Module, module: str) -> list[dict]:
    """All track_jit manifest entries in one module, source order."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            entry = describe_site(node, module)
            if entry is not None:
                out.append(entry)
    return out


_COMPARE_KEYS = (
    "static_argnums", "static_argnames", "partial_kwargs",
    "partial_pos", "donate",
)

#: per-key defaults for manifest entries missing a field (hand-edited
#: or older manifests) — matching describe_site's own defaults
_COMPARE_DEFAULTS: dict = {
    "static_argnums": [], "static_argnames": [], "partial_kwargs": [],
    "partial_pos": 0, "donate": False,
}


def _module_key(rel_path: str, manifest: dict) -> Optional[str]:
    """The manifest module suffix matching ``rel_path``, if any."""
    rel = rel_path.replace("\\", "/")
    for module, _name in manifest:
        if rel.endswith(module):
            return module
    return None


def check_module(
    tree: ast.Module, rel_path: str, emit,
    manifest: Optional[dict] = None,
) -> list[dict]:  # noqa: ANN001
    """TPL601 for one module; returns the module's sites for the
    project-wide passes."""
    if manifest is None:
        manifest = config.load_manifest()
    sites = iter_sites(tree, rel_path.replace("\\", "/"))
    if not sites:
        return sites
    module = _module_key(rel_path, manifest)
    for site in sites:
        entry = manifest.get((module, site["name"])) if module else None
        anchor = Anchor(site["line"])
        if entry is None:
            emit(
                anchor, "TPL601",
                f"track_jit({site['name']!r}, ...) has no manifest "
                f"entry",
            )
            continue
        diffs = [
            f"{key}: code={site[key]!r} manifest={entry.get(key)!r}"
            for key in _COMPARE_KEYS
            if site[key] != entry.get(key, _COMPARE_DEFAULTS[key])
        ]
        if diffs:
            emit(
                anchor, "TPL601",
                f"track_jit({site['name']!r}, ...) disagrees with its "
                f"manifest entry ({'; '.join(diffs)})",
            )
    return sites


def check_project(
    sites_by_path: dict[str, list[dict]], emit_at,
    manifest: Optional[dict] = None,
    attention_doc: Optional[Path] = None,
) -> None:  # noqa: ANN001
    """TPL602 + TPL603 over a whole analyzed file set.

    ``emit_at(path, line, code, detail)``.  Stale-entry detection only
    considers manifest modules that MATCH one of the analyzed files —
    linting a single file must not declare the rest of the manifest
    stale.
    """
    if manifest is None:
        manifest = config.load_manifest()
    if not manifest:
        return
    doc_path = attention_doc or config.ATTENTION_DOC
    doc_text = doc_path.read_text(encoding="utf-8") if doc_path.exists() \
        else None

    found: set[tuple[str, str]] = set()
    analyzed_modules: set[str] = set()
    for rel_path, sites in sites_by_path.items():
        rel = rel_path.replace("\\", "/")
        for module, _name in manifest:
            if rel.endswith(module):
                analyzed_modules.add(module)
                found.update(
                    (module, site["name"]) for site in sites
                )
    for (module, name), _entry in sorted(manifest.items()):
        if module in analyzed_modules and (module, name) not in found:
            emit_at(
                str(config.MANIFEST_PATH), 1, "TPL602",
                f"{module}:{name} (no track_jit site matches)",
            )
        if doc_text is not None and name not in doc_text:
            emit_at(
                str(doc_path), 1, "TPL603",
                f"{module}:{name} missing from {doc_path.name}",
            )


def build_manifest(paths: list[Path], root: Optional[Path] = None) -> dict:
    """Scan ``paths`` (files or directories) and build the manifest
    document for --write-lattice."""
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    entries = []
    for path in files:
        # manifest modules are PACKAGE-relative suffixes ("engine/
        # runner.py") so fixture trees in tests resolve against the
        # same entries — derived from the resolved path's components,
        # not a literal prefix, so `--write-lattice` produces the same
        # manifest from any cwd / absolute-path spelling
        parts = path.resolve().parts
        if "vllm_tgis_adapter_tpu" in parts:
            idx = len(parts) - 1 - parts[::-1].index(
                "vllm_tgis_adapter_tpu"
            )
            module = "/".join(parts[idx + 1:])
        elif root is not None:
            module = path.resolve().relative_to(
                Path(root).resolve()
            ).as_posix()
        else:
            module = path.as_posix()
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
        for site in iter_sites(tree, module):
            site.pop("line", None)
            entries.append(site)
    entries.sort(key=lambda e: (e["module"], e["name"]))
    return {
        "_comment": (
            "Compile-lattice manifest: every track_jit jit entry point "
            "with its static/partial-bound parameters.  tpulint TPL6xx "
            "diffs code against this file; regenerate after an "
            "INTENTIONAL jit change with `python -m tools.tpulint "
            "--write-lattice` and update docs/ATTENTION.md."
        ),
        "entries": entries,
    }


def write_manifest(paths: list[Path], out: Optional[Path] = None,
                   root: Optional[Path] = None) -> Path:
    target = out or config.MANIFEST_PATH
    doc = build_manifest(paths, root=root)
    target.write_text(
        json.dumps(doc, indent=2) + "\n", encoding="utf-8"
    )
    return target
