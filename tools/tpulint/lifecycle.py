"""TPL511/TPL512: static enforcement of the lifecycle grammar.

The reviewed manifest in ``tools/dettest/lifecycle_grammar.py``
declares every flight-recorder event kind (per-request DFA + batch
kinds) and every legal engine-lifecycle edge.  The runtime sanitizer
checks ORDER as events happen and the dettest explorer checks every
explored schedule; these two rules close the static corner so an
undeclared kind or edge cannot even be *written* without a manifest
diff showing up in review:

* **TPL511** — every ``<...recorder>.record("<kind>", ...)`` call site
  must use a kind declared somewhere in the manifest, and a kind
  declared batch-level (``decode``/``error``/``restart``/``stall``/
  ``doctor``) must never be recorded with a ``request_id`` (it would
  enter the per-request DFA it was deliberately excluded from).
* **TPL512** — lifecycle-transition call sites
  (``check_lifecycle_edge(old, new)``, ``_set_lifecycle(state)``) and
  direct ``*.lifecycle = <state>`` assignments must use declared
  states, and statically-known (old, new) pairs must be declared
  edges.  ``LIFECYCLE_SERVING``-style constants resolve to their
  lowercase suffix, so the supervisor's symbolic spellings are checked
  too; dynamically computed states are out of static reach (the
  runtime sanitizer owns those).
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.dettest import lifecycle_grammar
from tools.tpulint.astutil import call_bare_name

#: receiver names that mark a ``.record(...)`` call as a
#: flight-recorder record (``recorder.record``, ``self._recorder.record``,
#: ``rep.engine.recorder.record`` — the naming discipline for recorder
#: handles in this codebase).
_RECORDER_MARK = "recorder"

_SET_LIFECYCLE_NAMES = frozenset({"_set_lifecycle", "set_lifecycle"})

_LIFECYCLE_CONST_PREFIX = "LIFECYCLE_"


def _receiver_name(func: ast.expr) -> Optional[str]:
    """Last identifier of the receiver of ``recv.attr(...)``."""
    if not isinstance(func, ast.Attribute):
        return None
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def _state_str(node: ast.expr) -> Optional[str]:
    """Statically resolve a lifecycle-state expression: a string
    constant, or a ``LIFECYCLE_<STATE>`` symbolic name (its lowercase
    suffix).  None = not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    if name is not None and name.startswith(_LIFECYCLE_CONST_PREFIX):
        return name[len(_LIFECYCLE_CONST_PREFIX):].lower()
    return None


def _is_none(node: ast.expr) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def check_record_kinds(tree: ast.Module, rel_path: str, emit) -> None:  # noqa: ANN001
    """TPL511 over every recorder ``record()`` call of the module."""
    declared = lifecycle_grammar.all_kinds()
    per_request = lifecycle_grammar.request_kinds()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if call_bare_name(func) != "record":
            continue
        receiver = _receiver_name(func)
        if receiver is None or _RECORDER_MARK not in receiver.lower():
            continue
        if not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            continue  # dynamic kind: the runtime sanitizer's problem
        kind = first.value
        has_request_id = (
            len(node.args) > 1 and not _is_none(node.args[1])
        ) or any(
            kw.arg == "request_id" and not _is_none(kw.value)
            for kw in node.keywords
        )
        if kind not in declared:
            emit(
                node, "TPL511",
                f"kind {kind!r} is not declared in LIFECYCLE_MANIFEST",
            )
        elif has_request_id and kind not in per_request:
            emit(
                node, "TPL511",
                f"batch-level kind {kind!r} recorded with a request_id "
                f"(it has no per-request DFA edges)",
            )


def check_lifecycle_transitions(
    tree: ast.Module, rel_path: str, emit  # noqa: ANN001
) -> None:
    """TPL512 over transition call sites and lifecycle assignments."""
    states = lifecycle_grammar.engine_states()
    edges = lifecycle_grammar.engine_edges()
    entries = lifecycle_grammar.engine_entry_states()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_bare_name(node.func)
            if name == "check_lifecycle_edge" and len(node.args) >= 2:
                old = _state_str(node.args[0])
                new = _state_str(node.args[1])
                for state in (old, new):
                    if state is not None and state not in states:
                        emit(
                            node, "TPL512",
                            f"state {state!r} is not a declared "
                            f"lifecycle state",
                        )
                        break
                else:
                    if (
                        _is_none(node.args[0])
                        and new is not None
                        and new not in entries
                    ):
                        emit(
                            node, "TPL512",
                            f"{new!r} is not a declared entry state",
                        )
                    elif (
                        old is not None
                        and new is not None
                        and (old, new) not in edges
                    ):
                        emit(
                            node, "TPL512",
                            f"{old} -> {new} is not a declared "
                            f"lifecycle edge",
                        )
            elif name in _SET_LIFECYCLE_NAMES and node.args:
                state = _state_str(node.args[0])
                if state is not None and state not in states:
                    emit(
                        node, "TPL512",
                        f"state {state!r} is not a declared lifecycle "
                        f"state",
                    )
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            if not any(
                isinstance(t, ast.Attribute) and t.attr == "lifecycle"
                for t in targets
            ):
                continue
            state = _state_str(node.value) if node.value else None
            if state is not None and state not in states:
                emit(
                    node, "TPL512",
                    f"state {state!r} is not a declared lifecycle state",
                )


def check_module(tree: ast.Module, rel_path: str, emit) -> None:  # noqa: ANN001
    check_record_kinds(tree, rel_path, emit)
    check_lifecycle_transitions(tree, rel_path, emit)
