"""Rule table and project knowledge for tpulint.

Everything project-specific lives here — which modules sit on the engine
step loop, which model methods are jitted from other modules, which
helper calls are known to block the event loop — so the analyzer itself
stays a generic AST pass.
"""

from __future__ import annotations

import re

#: rule code → one-line description (docs/STATIC_ANALYSIS.md carries the
#: full rationale per rule; keep the two in sync — test_tpulint checks).
RULES: dict[str, str] = {
    "TPL000": "suppression without a reason: # tpulint: disable=CODE "
              "must carry (why) so the gate stays auditable",
    "TPL101": "Python branch on a traced value/shape inside a jitted "
              "function (every novel outcome re-traces and recompiles)",
    "TPL102": "f-string or dict key built from an array .shape inside a "
              "jitted function (shape-keyed control flow leaks retraces)",
    "TPL103": "likely-static control parameter (int/bool) jitted without "
              "static_argnums/static_argnames (recompile-by-value or "
              "tracer leak)",
    "TPL104": "jax.jit of a large-buffer entry point without a "
              "donate_argnums kwarg (transiently doubles HBM)",
    "TPL201": "explicit host synchronisation on the step path (.item(), "
              "jax.device_get, block_until_ready)",
    "TPL202": "implicit device→host pull on the step path (np.asarray/"
              "float()/int()/bool() on a device-array-named value)",
    "TPL301": "time.sleep inside async code (stalls every in-flight "
              "stream; use asyncio.sleep)",
    "TPL302": "synchronous file/network I/O inside async code (move it "
              "to asyncio.to_thread or a sync helper off the loop)",
    "TPL303": "known-blocking engine/device call on the event loop "
              "(dispatch via asyncio.to_thread like the step loop does)",
}

#: modules reachable from the engine step loop (engine/core.py →
#: runner.py → pipeline.py → ops/*): the TPL2xx host-sync scope.
#: Entries ending in "/" match directories, others match path suffixes.
STEP_LOOP_PATHS: tuple[str, ...] = (
    "engine/core.py",
    "engine/runner.py",
    "engine/pipeline.py",
    "engine/speculative.py",
    "engine/sampler.py",
    "ops/",
    "models/",
)

#: functions jitted from ANOTHER module (jax.jit(model.prefill) in
#: engine/runner.py), which call-site detection cannot see.  Keyed by
#: path suffix; values are qualnames within that file.
JIT_REGISTRY: dict[str, frozenset[str]] = {
    "models/llama.py": frozenset({
        "LlamaForCausalLM.prefill",
        "LlamaForCausalLM.prefill_chunk",
        # decode is jitted from the fused-wave builder
        # (runner._build_decode_fn) AND the speculative draft's propose
        # scan (engine/speculative.py _build_propose_fn)
        "LlamaForCausalLM.decode",
        # the unified mixed prefill+decode entry point
        # (ops/ragged_attention.py), jitted as runner._ragged_fn AND
        # from inside the speculative verify program
        # (runner._build_ragged_verify_fn, track_jit "ragged_verify")
        "LlamaForCausalLM.ragged_forward",
    }),
    # per-page quantize/dequantize movement ops (ops/kv_quant.py):
    # jitted from engine/runner.py as track_jit "gather_kv" /
    # "scatter_kv" — the host-tier / checkpoint / handoff page path,
    # one fixed block shape each, quantized caches included
    "ops/kv_quant.py": frozenset({
        "gather_kv_page",
        "restore_kv_page",
    }),
}

#: registry-method params that are static at every jit site (bound via
#: functools.partial or passed as Python scalars, never traced).
REGISTRY_STATIC_PARAMS: frozenset[str] = frozenset({
    "self", "block_size", "first_stage", "last_stage",
})

#: identifiers that mark a value as (probably) a live device array for
#: TPL202 — the documented naming discipline for device handles in this
#: codebase (packed result buffers, logits, KV caches, stage hiddens).
DEVICE_HINTS = re.compile(
    r"pack|logits|cache|hidden|handle|_dev\b|device", re.IGNORECASE
)

#: np.<fn>(x) that materialise x on host (one blocking transfer each).
HOST_PULLS: frozenset[str] = frozenset({"asarray", "array"})

#: builtin casts that force a scalar device→host round trip.
HOST_CASTS: frozenset[str] = frozenset({"float", "int", "bool"})

#: method calls that are *always* an explicit sync (TPL201).
SYNC_ATTR_CALLS: frozenset[str] = frozenset({"item", "block_until_ready"})

#: jit targets that move whole KV caches / weight-sized buffers and must
#: donate them (TPL104); zero-arg lambdas are exempt (nothing to donate).
LARGE_BUFFER = re.compile(r"prefill|decode|scatter|restore|cache")

#: synchronous I/O surfaces for TPL302: bare calls by name …
SYNC_IO_NAMES: frozenset[str] = frozenset({"open"})
#: … and method/attr calls.  Deliberately specific (``.read()`` alone is
#: too ambiguous — StreamReader.read is async).
SYNC_IO_ATTRS: frozenset[str] = frozenset({
    "read_text", "read_bytes", "write_text", "write_bytes",
    "urlopen", "load_cert_chain", "load_verify_locations",
    "check_output", "check_call",
})

#: project helpers known to block (device waits, file reads) that must
#: ride asyncio.to_thread when called from async code (TPL303).
BLOCKING_HELPERS: frozenset[str] = frozenset({
    "wait_step", "dispatch_step", "dispatch_chained_step", "precompile",
    "_tls_credentials", "block_until_ready",
})

#: time.sleep spelling for TPL301.
SLEEP_MODULES: frozenset[str] = frozenset({"time"})


def is_step_loop_module(rel_path: str) -> bool:
    """Does ``rel_path`` (posix, repo-relative) sit on the step loop?"""
    rel = rel_path.replace("\\", "/")
    for entry in STEP_LOOP_PATHS:
        if entry.endswith("/"):
            if rel.startswith(entry) or f"/{entry}" in rel:
                return True
        elif rel.endswith(entry):
            return True
    return False


def registry_qualnames(rel_path: str) -> frozenset[str]:
    """Registry-jitted qualnames for ``rel_path``, if any."""
    rel = rel_path.replace("\\", "/")
    for suffix, names in JIT_REGISTRY.items():
        if rel.endswith(suffix):
            return names
    return frozenset()
