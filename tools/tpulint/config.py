"""Rule table and project knowledge for tpulint.

Everything project-specific lives here — which modules sit on the engine
step loop, which model methods are jitted from other modules, which
helper calls are known to block the event loop — so the analyzer itself
stays a generic AST pass.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Optional

#: rule code → one-line description (docs/STATIC_ANALYSIS.md carries the
#: full rationale per rule; keep the two in sync — test_tpulint checks).
RULES: dict[str, str] = {
    "TPL000": "suppression without a reason: # tpulint: disable=CODE "
              "must carry (why) so the gate stays auditable",
    "TPL101": "Python branch on a traced value/shape inside a jitted "
              "function (every novel outcome re-traces and recompiles)",
    "TPL102": "f-string or dict key built from an array .shape inside a "
              "jitted function (shape-keyed control flow leaks retraces)",
    "TPL103": "likely-static control parameter (int/bool) jitted without "
              "static_argnums/static_argnames (recompile-by-value or "
              "tracer leak)",
    "TPL104": "jax.jit of a large-buffer entry point without a "
              "donate_argnums kwarg (transiently doubles HBM)",
    "TPL201": "explicit host synchronisation on the step path (.item(), "
              "jax.device_get, block_until_ready)",
    "TPL202": "implicit device→host pull on the step path (np.asarray/"
              "float()/int()/bool() on a device-array-named value)",
    "TPL301": "time.sleep inside async code (stalls every in-flight "
              "stream; use asyncio.sleep)",
    "TPL302": "synchronous file/network I/O inside async code (move it "
              "to asyncio.to_thread or a sync helper off the loop)",
    "TPL303": "known-blocking engine/device call on the event loop "
              "(dispatch via asyncio.to_thread like the step loop does)",
    "TPL304": "asyncio.wait_for(event.wait(), ...): on py3.10 "
              "bpo-42130 swallows the timeout cancellation when the "
              "event is already set, so the wait can outlive its "
              "deadline — gate the loop on a re-checked stop flag or "
              "await a fresh per-wake future instead",
    "TPL401": "await of a non-to_thread awaitable while holding an "
              "engine lock (an arbitrary suspension under a "
              "step-loop-scoped lock extends the critical section "
              "unboundedly and invites lock-order deadlocks)",
    "TPL402": "lock-order cycle: these locks are acquired in "
              "conflicting orders across the engine (two tasks each "
              "holding one half deadlock the step loop)",
    "TPL403": "shared attribute written from both event-loop and "
              "worker-thread context without a common lock (torn "
              "accounting: the PR 9/PR 14 transfer-path bug class)",
    "TPL501": "resource acquired but not released on every exit path: "
              "put the release in try/finally or a context manager "
              "(an exception between the pair leaks the pin/charge/"
              "epoch forever)",
    "TPL502": "raw asyncio task spawn: the event loop holds only weak "
              "task refs, so an untracked create_task can be "
              "garbage-collected mid-flight; spawn through "
              "utils.spawn_task",
    "TPL511": "flight-recorder record() call with an event kind not "
              "declared in the lifecycle grammar "
              "(tools/dettest/lifecycle_grammar.py LIFECYCLE_MANIFEST) "
              "— a new kind must land as a reviewed manifest diff, and "
              "a batch-level kind must never carry a request_id",
    "TPL512": "engine lifecycle transition with a state or edge not "
              "declared in the lifecycle grammar's engine machine "
              "(tools/dettest/lifecycle_grammar.py LIFECYCLE_MANIFEST "
              "engine_lifecycle) — the supervisor may only move along "
              "declared edges",
    "TPL601": "jit entry point absent from (or disagreeing with) "
              "tools/tpulint/lattice_manifest.json: regenerate with "
              "python -m tools.tpulint --write-lattice and update "
              "docs/ATTENTION.md expected-compile counts",
    "TPL602": "stale compile-lattice manifest entry: no track_jit site "
              "matches it (regenerate with --write-lattice)",
    "TPL603": "compile-lattice manifest entry undocumented in "
              "docs/ATTENTION.md (the expected-compile table must "
              "name every jit entry point)",
}

#: modules reachable from the engine step loop (engine/core.py →
#: runner.py → pipeline.py → ops/*): the TPL2xx host-sync scope.
#: Entries ending in "/" match directories, others match path suffixes.
STEP_LOOP_PATHS: tuple[str, ...] = (
    "engine/core.py",
    "engine/runner.py",
    "engine/pipeline.py",
    "engine/speculative.py",
    "engine/sampler.py",
    "ops/",
    "models/",
)

#: functions jitted from ANOTHER module (jax.jit(model.prefill) in
#: engine/runner.py), which call-site detection cannot see.  Keyed by
#: path suffix; values are qualnames within that file.
JIT_REGISTRY: dict[str, frozenset[str]] = {
    "models/llama.py": frozenset({
        "LlamaForCausalLM.prefill",
        "LlamaForCausalLM.prefill_chunk",
        # decode is jitted from the fused-wave builder
        # (runner._build_decode_fn) AND the speculative draft's propose
        # scan (engine/speculative.py _build_propose_fn)
        "LlamaForCausalLM.decode",
        # the unified mixed prefill+decode entry point
        # (ops/ragged_attention.py), jitted as runner._ragged_fn AND
        # from inside the speculative verify program
        # (runner._build_ragged_verify_fn, track_jit "ragged_verify")
        "LlamaForCausalLM.ragged_forward",
    }),
    # per-page quantize/dequantize movement ops (ops/kv_quant.py):
    # jitted from engine/runner.py as track_jit "gather_kv" /
    # "scatter_kv" — the host-tier / checkpoint / handoff page path,
    # one fixed block shape each, quantized caches included
    "ops/kv_quant.py": frozenset({
        "gather_kv_page",
        "restore_kv_page",
    }),
}

#: registry-method params that are static at every jit site (bound via
#: functools.partial or passed as Python scalars, never traced).
REGISTRY_STATIC_PARAMS: frozenset[str] = frozenset({
    "self", "block_size", "first_stage", "last_stage",
})

#: identifiers that mark a value as (probably) a live device array for
#: TPL202 — the documented naming discipline for device handles in this
#: codebase (packed result buffers, logits, KV caches, stage hiddens).
DEVICE_HINTS = re.compile(
    r"pack|logits|cache|hidden|handle|_dev\b|device", re.IGNORECASE
)

#: np.<fn>(x) that materialise x on host (one blocking transfer each).
HOST_PULLS: frozenset[str] = frozenset({"asarray", "array"})

#: builtin casts that force a scalar device→host round trip.
HOST_CASTS: frozenset[str] = frozenset({"float", "int", "bool"})

#: method calls that are *always* an explicit sync (TPL201).
SYNC_ATTR_CALLS: frozenset[str] = frozenset({"item", "block_until_ready"})

#: jit targets that move whole KV caches / weight-sized buffers and must
#: donate them (TPL104); zero-arg lambdas are exempt (nothing to donate).
LARGE_BUFFER = re.compile(r"prefill|decode|scatter|restore|cache")

#: synchronous I/O surfaces for TPL302: bare calls by name …
SYNC_IO_NAMES: frozenset[str] = frozenset({"open"})
#: … and method/attr calls.  Deliberately specific (``.read()`` alone is
#: too ambiguous — StreamReader.read is async).
SYNC_IO_ATTRS: frozenset[str] = frozenset({
    "read_text", "read_bytes", "write_text", "write_bytes",
    "urlopen", "load_cert_chain", "load_verify_locations",
    "check_output", "check_call",
})

#: project helpers known to block (device waits, file reads) that must
#: ride asyncio.to_thread when called from async code (TPL303).
BLOCKING_HELPERS: frozenset[str] = frozenset({
    "wait_step", "dispatch_step", "dispatch_chained_step", "precompile",
    "_tls_credentials", "block_until_ready",
})

#: time.sleep spelling for TPL301.
SLEEP_MODULES: frozenset[str] = frozenset({"time"})

# ---------------------------------------------------------------- TPL4xx

#: modules whose locks are "engine locks" for the TPL4xx family: the
#: replica/step-loop locks, the tier transfer lock, the adapter stream
#: lock, and the supervisor/frontdoor machinery that serializes against
#: them.  Entries ending in "/" match directories, others path suffixes.
LOCK_SCOPE_PATHS: tuple[str, ...] = (
    "engine/",
    "supervisor/",
    "frontdoor/",
)

#: names that identify a with-statement context expression as a lock
#: (``self._transfer_lock``, ``rep.lock``, module-global ``_lock``,
#: ``self._sema`` — semaphores serialize exactly like locks here).
LOCK_NAME = re.compile(r"lock|sema|mutex", re.IGNORECASE)

#: awaitees that are sanctioned under a held lock (TPL401): worker-thread
#: offloads — the lock exists precisely to serialize these.
ALLOWED_AWAITS_UNDER_LOCK: frozenset[str] = frozenset({"to_thread"})

# ---------------------------------------------------------------- TPL5xx

#: acquire → release method pairs (TPL501).  The rule fires when BOTH
#: ends appear in one function and the release is not on every exit path
#: (not inside a ``finally``); cross-function protocols (pin at
#: admission, unpin at finish) are lifecycle contracts the runtime
#: sanitizer checks instead (engine/sanitizer.py).
RESOURCE_PAIRS: dict[str, str] = {
    "charge_adapter": "release_adapter",   # arena adapter charges
    "pin": "unpin",                        # LoRA registry refcounts
    "allocate": "free",                    # KV page allocator
    "begin_free_epoch": "flush_free_epoch",  # chained-decode quarantine
    "begin_dispatch": "end_dispatch",      # compile-tracker in-flight
    "arm_site": "disarm",                  # failpoints
    "arm": "disarm",
    "acquire": "release",                  # bare lock/semaphore protocol
}

#: modules allowed to call asyncio's raw ``create_task`` (TPL502): the
#: home of the shared strong-ref spawn helper itself.
TASK_HELPER_MODULES: tuple[str, ...] = ("utils.py",)

#: the sanctioned spawn wrapper every other module must use.
TASK_HELPER_NAME = "spawn_task"

# ---------------------------------------------------------------- TPL6xx

#: checked-in compile-lattice manifest: every ``track_jit`` entry point
#: with its static/partial-bound parameters.  Regenerate after an
#: intentional jit change with ``python -m tools.tpulint
#: --write-lattice`` (docs/STATIC_ANALYSIS.md "Compile-lattice
#: manifest").
MANIFEST_PATH = Path(__file__).resolve().parent / "lattice_manifest.json"

#: the doc that carries the expected-compile-count table (TPL603).
ATTENTION_DOC = (
    Path(__file__).resolve().parents[2] / "docs" / "ATTENTION.md"
)


def load_manifest(path: Optional[Path] = None) -> dict:
    """The manifest as ``{(module, name): entry_dict}`` (empty when the
    file is absent — the --write-lattice bootstrap case)."""
    import json

    p = path or MANIFEST_PATH
    if not p.exists():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    return {
        (e["module"], e["name"]): e for e in data.get("entries", [])
    }


def _path_in(rel_path: str, entries: tuple[str, ...]) -> bool:
    rel = rel_path.replace("\\", "/")
    for entry in entries:
        if entry.endswith("/"):
            if rel.startswith(entry) or f"/{entry}" in rel:
                return True
        elif rel.endswith(entry):
            return True
    return False


def is_step_loop_module(rel_path: str) -> bool:
    """Does ``rel_path`` (posix, repo-relative) sit on the step loop?"""
    return _path_in(rel_path, STEP_LOOP_PATHS)


def is_lock_scope_module(rel_path: str) -> bool:
    """Is ``rel_path`` in the TPL4xx lock-discipline scope?"""
    return _path_in(rel_path, LOCK_SCOPE_PATHS)


def is_task_helper_module(rel_path: str) -> bool:
    """Is ``rel_path`` the sanctioned raw-create_task module (TPL502)?

    Exact path-component match — ``engine/io_utils.py`` must NOT
    inherit ``utils.py``'s exemption via a bare suffix test."""
    rel = rel_path.replace("\\", "/")
    return any(
        rel == entry or rel.endswith(f"/{entry}")
        for entry in TASK_HELPER_MODULES
    )


def registry_qualnames(rel_path: str) -> frozenset[str]:
    """Registry-jitted qualnames for ``rel_path``, if any."""
    rel = rel_path.replace("\\", "/")
    for suffix, names in JIT_REGISTRY.items():
        if rel.endswith(suffix):
            return names
    return frozenset()
