"""TPL5xx: resource acquire/release pairing.

* **TPL501** — a function that both acquires and releases one of the
  known resource pairs (``config.RESOURCE_PAIRS``: arena charges, LoRA
  pins, allocator pages, free epochs, failpoint arms, bare lock
  protocol) must put the release on EVERY exit path: a matching release
  that only runs on the fall-through path leaks the resource the moment
  anything between the pair raises — the PR 5 exception-traceback
  KV-pool pin, the ISSUE 9 GC'd-ticket park.  The fix is ``try/finally``
  or a context manager.  Cross-function protocols (pin at admission /
  unpin at finish) are lifecycle contracts checked at runtime by
  ``engine/sanitizer.py`` instead.
* **TPL502** — every ``asyncio.create_task`` (or ``loop.create_task`` /
  ``ensure_future``) call outside ``utils.py`` (the home of the shared
  strong-ref helper).  The event loop holds only weak task references,
  so a task not retained in a strong-ref container can be
  garbage-collected mid-flight — the PR 9 GC'd-promotion-task bug.
  ``utils.spawn_task`` retains every task it spawns until done.
"""

from __future__ import annotations

import ast
from typing import Optional, Union

from tools.tpulint import config
from tools.tpulint.astutil import call_bare_name

_FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

_RAW_SPAWNS = frozenset({"create_task", "ensure_future"})


def _own_body_calls(fn: _FuncNode) -> list[tuple[str, ast.Call, bool]]:
    """(bare_name, call, in_finally) for calls in ``fn``'s own body —
    nested function/class definitions are skipped (they run in another
    context), and ``in_finally`` is tracked through arbitrarily nested
    compound statements."""
    out: list[tuple[str, ast.Call, bool]] = []
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
            ast.Lambda)

    def visit(node: ast.AST, in_finally: bool) -> None:
        if isinstance(node, skip):
            return
        if isinstance(node, ast.Call):
            name = call_bare_name(node.func)
            if name is not None:
                out.append((name, node, in_finally))
        if isinstance(node, ast.Try):
            for s in (*node.body, *node.orelse):
                visit(s, in_finally)
            for handler in node.handlers:
                for s in handler.body:
                    visit(s, in_finally)
            for s in node.finalbody:
                visit(s, True)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, in_finally)

    for stmt in fn.body:
        visit(stmt, False)
    return out


def check_pairing(tree: ast.Module, rel_path: str, emit) -> None:  # noqa: ANN001
    """TPL501 over every function of the module."""
    for fn in [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]:
        calls = _own_body_calls(fn)
        names = {name for name, _, _ in calls}
        for acquire, release in config.RESOURCE_PAIRS.items():
            if acquire == release:
                continue
            if acquire not in names or release not in names:
                continue  # cross-function protocol: not this rule's job
            # every acquire needs its own finally-guarded release: one
            # correctly guarded pair must not whitelist a second,
            # unguarded pair of the same names in the same function
            acquires = sum(1 for name, _, _ in calls if name == acquire)
            guarded = sum(
                1 for name, _, in_finally in calls
                if name == release and in_finally
            )
            if guarded >= acquires:
                continue
            site = next(
                call for name, call, _ in calls if name == acquire
            )
            emit(
                site, "TPL501",
                f"{acquire}()/{release}() in {fn.name!r} without a "
                f"finally-guarded release for every acquire "
                f"({acquires} acquire(s), {guarded} finally-guarded "
                f"release(s))",
            )


def check_task_spawns(tree: ast.Module, rel_path: str, emit) -> None:  # noqa: ANN001
    """TPL502 over every call of the module."""
    if config.is_task_helper_module(rel_path):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_bare_name(node.func)
        if name in _RAW_SPAWNS:
            emit(
                node, "TPL502",
                f"{name}(...) — use "
                f"{config.TASK_HELPER_NAME}(coro, name=..., "
                f"retain=...) instead",
            )
