"""Tiny AST helpers shared by the tpulint rule families."""

from __future__ import annotations

import ast
from typing import Optional


def call_bare_name(func: ast.expr) -> Optional[str]:
    """The callable's last-segment name: ``foo`` for ``foo(...)``,
    ``bar`` for ``obj.attr.bar(...)``; None for computed callees."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


class Anchor:
    """Minimal node stand-in carrying a location for finding emitters
    (rules that anchor to a line they computed, not an AST node)."""

    def __init__(self, lineno: int, col_offset: int = 0):
        self.lineno = lineno
        self.col_offset = col_offset
