"""Trace capture replay + synthetic arrival processes (ISSUE 16c).

Replays a ``--capture-trace`` JSONL file (arrival offsets, token
counts, tenant/class/adapter — shapes, never content) against a real
in-process engine, or synthesizes one of three seeded arrival
processes:

* ``diurnal`` — sinusoidal rate over the span (the daily curve);
* ``bursty`` — clustered arrivals around a few burst instants (the
  retry-storm / fan-out shape);
* ``flash_crowd`` — a low base rate, then most of the traffic landing
  inside a narrow spike window (the launch-day shape).

All processes are deterministic per ``--seed``.  Request classes ride
the ``x-request-class`` header, so the replay exercises exactly the
admission path production traffic takes (http/grpc → telemetry/slo.py
class resolution → per-class attainment).

``--check`` is the ``nox -s slo_check`` gate, two phases:

1. the checked-in reference bursty trace
   (``tools/traces/reference_bursty.jsonl``) must MEET the default
   chat TTFT/ITL objectives — live ``slo_burn_rate{class=chat}``
   gauge < 1.0 and attainment ≥ 0.99 — and the cost ledger must
   conserve tokens (Σ per-tenant ledger output tokens == tokens the
   streams delivered);
2. a flash-crowd burst against a deliberately tiny engine with a tight
   declared TTFT objective (``--slo-config``) must DRIVE
   ``slo_burn_rate{class=chat}`` above 1.0 — the gate proves the
   signal fires, not just that it stays quiet.

Run ``python tools/trace_replay.py --write-reference`` to regenerate
the checked-in trace (same seed, byte-identical).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import random
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TGIS_TPU_SANITIZE", "1")

REFERENCE_TRACE = str(
    Path(__file__).resolve().parent / "traces" / "reference_bursty.jsonl"
)

#: nothing may outlive this per phase (mirrors tools/scenarios.py)
REPLAY_BOUND_S = 120.0

PROCESSES = ("diurnal", "bursty", "flash_crowd")


# --------------------------------------------------------------- traces


def load_trace(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    records.sort(key=lambda r: r.get("offset_s", 0.0))
    return records


def synthesize(
    kind: str, *, seed: int = 0, n_requests: int = 24, span_s: float = 4.0
) -> list[dict]:
    """One seeded arrival process → capture-shaped records (the same
    fields ``--capture-trace`` writes, minus the outcome columns)."""
    rng = random.Random(seed)
    offsets: list[float] = []
    if kind == "diurnal":
        # thinning against rate(t) ∝ 1 + 0.8·sin(2πt/span): the
        # accepted points follow the sinusoid exactly, seeded
        while len(offsets) < n_requests:
            t = rng.uniform(0.0, span_s)
            accept = (1.0 + 0.8 * math.sin(2 * math.pi * t / span_s)) / 1.8
            if rng.random() < accept:
                offsets.append(t)
    elif kind == "bursty":
        n_bursts = max(1, n_requests // 8)
        burst_times = sorted(
            rng.uniform(0.0, span_s * 0.8) for _ in range(n_bursts)
        )
        for i in range(n_requests):
            offsets.append(
                burst_times[i % n_bursts] + rng.uniform(0.0, 0.25)
            )
    elif kind == "flash_crowd":
        spike_at = span_s * 0.6
        for i in range(n_requests):
            if i < n_requests // 4:  # the quiet lead-in
                offsets.append(rng.uniform(0.0, spike_at))
            else:  # the crowd arrives inside a 5%-of-span window
                offsets.append(spike_at + rng.uniform(0.0, span_s * 0.05))
    else:
        raise ValueError(f"unknown arrival process {kind!r}")
    offsets.sort()
    records = []
    for i, off in enumerate(offsets):
        cls = "rag" if i % 5 == 4 else "chat"
        records.append({
            "offset_s": round(off, 3),
            "request_id": f"{kind}-{i}",
            "tenant": ("t-a", "t-b")[i % 2],
            "class": cls,
            "adapter": None,
            "prompt_tokens": (
                rng.randint(6, 20) if cls == "chat" else rng.randint(24, 40)
            ),
            "max_tokens": rng.randint(6, 14),
            "temperature": 0.0,
        })
    return records


def write_reference(path: str = REFERENCE_TRACE) -> str:
    """(Re)generate the checked-in slo_check reference trace —
    deterministic, so a regeneration is byte-identical."""
    records = synthesize("bursty", seed=16, n_requests=20, span_s=3.0)
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


# --------------------------------------------------------------- replay


def _prompt_ids(index: int, n_tokens: int) -> list[int]:
    """Deterministic stand-in prompt of the captured LENGTH (captures
    never carry content — only shapes replay)."""
    return [3 + (17 * index + j) % 300 for j in range(max(1, n_tokens))]


async def _drive(engine, rec: dict, index: int) -> dict:  # noqa: ANN001
    """One request to its terminal outcome, class via the SAME
    x-request-class header production traffic uses."""
    from vllm_tgis_adapter_tpu.engine.sampling_params import (
        RequestOutputKind,
        SamplingParams,
    )

    params = SamplingParams(
        temperature=float(rec.get("temperature") or 0.0),
        max_tokens=int(
            rec.get("max_tokens") or rec.get("output_tokens") or 8
        ),
        ignore_eos=True,
        output_kind=RequestOutputKind.DELTA,
    )
    rid = f"replay-{index}-{rec.get('request_id', index)}"
    tokens = 0
    t0 = time.perf_counter()
    ttft = None
    try:
        async for out in engine.generate(
            prompt=None,
            sampling_params=params,
            request_id=rid,
            prompt_token_ids=_prompt_ids(
                index, int(rec.get("prompt_tokens") or 8)
            ),
            trace_headers={"x-request-class": rec.get("class", "chat")},
            tenant_id=rec.get("tenant"),
        ):
            new = len(out.outputs[0].token_ids) if out.outputs else 0
            if new and ttft is None:
                ttft = time.perf_counter() - t0
            tokens += new
        return {"ok": True, "tokens": tokens, "ttft_s": ttft}
    except BaseException as e:  # noqa: BLE001 — the outcome IS the result
        return {"ok": False, "tokens": tokens, "error": repr(e)}


async def replay(
    engine, records: list[dict], *, speedup: float = 1.0  # noqa: ANN001
) -> list[dict]:
    """Open-loop replay: each record fires at its captured offset
    (compressed by ``speedup``), concurrency emerges from the arrival
    process — the property that makes a replay a load test rather than
    a closed-loop benchmark."""

    async def fire(i: int, rec: dict) -> dict:
        await asyncio.sleep(
            max(0.0, float(rec.get("offset_s") or 0.0)) / max(speedup, 1e-9)
        )
        return await _drive(engine, rec, i)

    tasks = [
        asyncio.create_task(fire(i, rec))
        for i, rec in enumerate(records)
    ]
    return await asyncio.wait_for(asyncio.gather(*tasks), REPLAY_BOUND_S)


def _burn_gauge(cls: str, window: str = "5m") -> float:
    """Read the LIVE exported gauge (not the SloEngine internals): the
    gate asserts what an operator's alerting would actually see."""
    from vllm_tgis_adapter_tpu import metrics

    return metrics.slo_burn_rate.labels(cls, window)._value.get()  # noqa: SLF001


def _attainment_gauge(cls: str, objective: str) -> float:
    from vllm_tgis_adapter_tpu import metrics

    return metrics.slo_attainment.labels(cls, objective)._value.get()  # noqa: SLF001


# ---------------------------------------------------------------- check


async def slo_check(model_dir: str) -> dict:
    """The two-phase ``nox -s slo_check`` gate (module docstring)."""
    from tools.scenarios import build_engine

    import jax

    # CPU-proxy fidelity (bench.py discipline): synchronous dispatch
    # behaves like an accelerator stream
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    # ---- phase 1: the reference trace meets the default objectives
    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=8,
        prefill_buckets=(32, 64), supervised=False,
    )
    try:
        records = load_trace(REFERENCE_TRACE)
        # warm passes compile every serving shape off the clock (never
        # time a compile).  The flood pass — the FULL trace with
        # offsets stripped — compiles the peak-batch shapes (packed
        # prefill, decode at full width) in one burst.  But a flood
        # alone under-covers: packed admission swallows solo prefill
        # buckets and full-width decode hides the short chained-step
        # variants, so a paced run still hits cold shapes mid-
        # measurement.  Follow-up warm passes therefore replay the
        # trace at the MEASURED pacing — identical offsets and speedup
        # reproduce the measured pass's batch/step mix — and repeat
        # until one pass closes the shape lattice (compiles nothing
        # new); a compile stall inside a warm pass perturbs its own
        # scheduling, so a single paced pass is not always enough.
        # The ``__warmup`` id prefix exempts these passes from the SLO
        # feeds (core.py TTFT/ITL, async_llm.py availability) — warm
        # compile stalls must not burn the error budget the measured
        # pass is gated on — while the ledger still bills them, so the
        # conservation check below covers warm tokens too.
        from vllm_tgis_adapter_tpu import compile_tracker

        warm_results = await replay(
            engine,
            [
                {**rec, "offset_s": 0.0, "request_id": f"__warmup-flood-{i}"}
                for i, rec in enumerate(records)
            ],
        )
        for attempt in range(4):
            before = compile_tracker.num_shapes()
            warm_results += await replay(
                engine,
                [
                    {**rec, "request_id": f"__warmup-paced-{attempt}-{i}"}
                    for i, rec in enumerate(records)
                ],
                speedup=2.0,
            )
            if compile_tracker.num_shapes() == before:
                break
        results = await replay(engine, records, speedup=2.0)
        engine.refresh_engine_gauges()
        failures = [r for r in results if not r["ok"]]
        # conservation is against EVERYTHING the engine delivered —
        # the warm pass is billed too
        streamed = sum(r["tokens"] for r in results + warm_results)
        ledger_out = sum(
            cls_totals["tokens_out"]
            for classes in engine.ledger.tenant_totals().values()
            for cls_totals in classes.values()
        )
        phase1 = {
            "requests": len(results),
            "failures": len(failures),
            "chat_burn_5m": round(_burn_gauge("chat"), 4),
            "chat_ttft_attainment": round(
                _attainment_gauge("chat", "ttft"), 4
            ),
            "chat_itl_attainment": round(
                _attainment_gauge("chat", "itl"), 4
            ),
            "streamed_tokens": streamed,
            "ledger_tokens_out": ledger_out,
            "ledger_open": engine.ledger.open_count,
        }
    finally:
        await engine.stop()
    ok1 = (
        phase1["failures"] == 0
        and phase1["chat_burn_5m"] < 1.0
        and phase1["chat_ttft_attainment"] >= 0.99
        and phase1["chat_itl_attainment"] >= 0.99
        and phase1["ledger_open"] == 0
        and phase1["ledger_tokens_out"] == phase1["streamed_tokens"]
    )

    # ---- phase 2: a flash crowd against a tight declared objective
    # must drive the burn gauge ABOVE 1.0 (the alert fires)
    engine = build_engine(
        model_dir, num_blocks=96, max_seqs=2,
        prefill_buckets=(32, 64), supervised=False,
        slo_config='{"chat": {"ttft_p99_s": 0.05}}',
    )
    try:
        crowd = synthesize(
            "flash_crowd", seed=7, n_requests=16, span_s=2.0
        )
        await replay(engine, crowd)
        engine.refresh_engine_gauges()
        phase2 = {
            "requests": len(crowd),
            "chat_burn_5m": round(_burn_gauge("chat"), 4),
        }
    finally:
        await engine.stop()
    ok2 = phase2["chat_burn_5m"] > 1.0

    return {
        "kind": "slo_check",
        "phase1_reference_trace": phase1,
        "phase1_ok": ok1,
        "phase2_overload": phase2,
        "phase2_ok": ok2,
        "ok": ok1 and ok2,
    }


# ----------------------------------------------------------------- main


async def run_once(
    model_dir: str,
    records: list[dict],
    *,
    speedup: float,
    slo_config: str | None,
) -> dict:
    """Non-gating entry: replay ``records`` and report attainment/burn
    per class plus the ledger's tenant totals."""
    from tools.scenarios import build_engine

    engine = build_engine(
        model_dir, num_blocks=192, max_seqs=8,
        prefill_buckets=(32, 64), supervised=False,
        slo_config=slo_config,
    )
    try:
        t0 = time.perf_counter()
        results = await replay(engine, records, speedup=speedup)
        wall = time.perf_counter() - t0
        engine.refresh_engine_gauges()
        slo = engine.slo_engine
        return {
            "kind": "trace_replay",
            "requests": len(results),
            "failures": sum(1 for r in results if not r["ok"]),
            "streamed_tokens": sum(r["tokens"] for r in results),
            "wall_s": round(wall, 3),
            "burn_5m": {
                cls: round(slo.burn_rate(cls, "5m"), 4)
                for cls in slo.objectives
            },
            "ledger": engine.ledger.tenant_totals(),
        }
    finally:
        await engine.stop()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--trace", default=None,
                        help="captured --capture-trace JSONL to replay")
    parser.add_argument("--synthesize", default=None, choices=PROCESSES,
                        help="synthesize this arrival process instead "
                             "of replaying a capture")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--requests", type=int, default=24,
                        help="synthetic request count")
    parser.add_argument("--span", type=float, default=4.0,
                        help="synthetic arrival span in seconds")
    parser.add_argument("--speedup", type=float, default=1.0,
                        help="compress captured offsets by this factor")
    parser.add_argument("--slo-config", default=None,
                        help="objectives JSON forwarded to the engine")
    parser.add_argument("--check", action="store_true",
                        help="run the two-phase nox -s slo_check gate "
                             "and exit nonzero on failure")
    parser.add_argument("--write-reference", action="store_true",
                        help="regenerate the checked-in reference "
                             "trace (deterministic) and exit")
    args = parser.parse_args(argv)

    if args.write_reference:
        print(write_reference())
        return 0

    from tools.scenarios import build_fixtures

    model_dir, _adapter_dir = build_fixtures()
    if args.check:
        line = asyncio.run(slo_check(model_dir))
        print(json.dumps(line))
        return 0 if line["ok"] else 1

    if args.synthesize:
        records = synthesize(
            args.synthesize, seed=args.seed,
            n_requests=args.requests, span_s=args.span,
        )
    else:
        records = load_trace(args.trace or REFERENCE_TRACE)
    line = asyncio.run(run_once(
        model_dir, records,
        speedup=args.speedup, slo_config=args.slo_config,
    ))
    print(json.dumps(line))
    return 0 if line["failures"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
