"""Perf regression gate: ``nox -s perf_check`` (ROADMAP item 5, minimal core).

Runs the closed-loop mini-bench (bench.py machinery, CPU proxy,
BENCH_TINY-sized) once per serving data path and diffs the results
against the checked-in ``PERF_BASELINE.json``:

* ``aggregate_output_tok_per_s`` — fails on > ``tolerance`` (default
  20%) regression against the baseline, using the BEST of ``runs``
  short passes per backend to damp scheduler/load jitter (the r05
  lesson: a single 0.5s timed pass swings 3x run-to-run, which is how
  the 1847 → 466 drop went unattributed for a round — BASELINE.md
  "Perf regression log");
* ``padding_waste_frac`` — fails when the padding fraction grows more
  than ``waste_slack`` absolute over the baseline (the ragged backend's
  whole claim is waste ≈ 0; a silent return of bucket padding is a
  regression even if tok/s survives);
* speculative decoding (docs/ATTENTION.md "Speculative decoding"): the
  decode-heavy chat scenario under concurrent RAG prefill load run with
  and without a same-weights draft — spec chat ITL p50 must beat plain
  ragged by ≥ ``spec.min_itl_speedup`` (ISSUE 12 acceptance: 1.5×) at
  acceptance ≥ ``spec.min_acceptance`` with identical greedy outputs;
* dp scaling (docs/SCALING.md): aggregate tok/s across the baseline's
  ``dp.points`` replica counts (ragged backend, BENCH_ARCH=small +
  BENCH_SYNC_DISPATCH=1 — see bench.py's docstring for why the dp gate
  needs both), gated on absolute floors AND the dp=N / dp=1 scaling
  ratios in ``dp.min_scaling`` (ISSUE 7 acceptance: dp=2 ≥ 1.6x,
  dp=4 ≥ 2.8x).  Ratio gates are robust to shared-runner load jitter
  (both points see the same load); the floors catch a uniformly slow
  fleet.

* prefill/decode disaggregation (docs/SCALING.md "Disaggregated
  roles"): the BENCH_ROLES chat+RAG scenario run disaggregated AND
  all-mixed at equal replica count — disaggregated chat ITL p99 must
  stay ≤ ``disagg.max_itl_ratio`` (default 0.5×, i.e. ≥ 2× better) of
  the mixed fleet's, handoff streams token-identical (outputs digest),
  every handoff taken with zero fallbacks.

Exit codes follow obs_check: 0 green, 1 regression, 2 tool error.
Update the baseline deliberately with ``--write`` after a reviewed
perf-relevant change; the JSON records the config knobs it was
measured under.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
BASELINE_PATH = REPO_ROOT / "PERF_BASELINE.json"


def run_bench(backend: str, env_overrides: dict) -> dict:
    env = dict(os.environ)
    env.update(env_overrides)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_TINY"] = "1"
    env["BENCH_ATTENTION_BACKEND"] = backend
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "bench.py")],
        capture_output=True, text=True, timeout=600, env=env,
    )
    line = None
    for candidate in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(candidate)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            line = parsed
            break
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"bench.py ({backend}) failed rc={proc.returncode}: "
            f"{proc.stderr[-400:]}"
        )
    if "error" in line:
        raise RuntimeError(f"bench.py ({backend}) errored: {line['error']}")
    return line


def measure(backend: str, runs: int, env_overrides: dict) -> dict:
    best = None
    for _ in range(runs):
        line = run_bench(backend, env_overrides)
        if best is None or line["value"] > best["value"]:
            best = line
    return {
        "aggregate_output_tok_per_s": best["value"],
        "padding_waste_frac": best["padding_waste_frac"],
        "compiled_shapes": best["compiled_shapes"],
        "weight_resident_bytes": best.get("weight_resident_bytes"),
    }


def measure_dp(dp_cfg: dict, runs: int) -> dict[str, dict]:
    """Best-of-``runs`` bench line per replica count in ``dp_cfg``."""
    backend = dp_cfg.get("backend", "ragged")
    results: dict[str, dict] = {}
    for point in dp_cfg.get("points", []):
        env = dict(dp_cfg.get("env", {}))
        env["BENCH_DP"] = str(point)
        best = None
        for _ in range(runs):
            line = run_bench(backend, env)
            if best is None or line["value"] > best["value"]:
                best = line
        results[str(point)] = best
        print(
            f"perf_check: dp={point}     "
            f"tok/s={best['value']:8.1f} "
            f"per_replica={best.get('per_replica_committed_tok_per_s')} "
            f"affinity_hits={best.get('placement_affinity_hit_rate')}"
        )
    return results


def measure_lora(lora_cfg: dict, runs: int) -> tuple[dict, dict]:
    """Best-of-``runs`` adapter-churn line + single-adapter line
    (docs/LORA.md; the churn line is the acceptance demo: 128
    registered / 16 resident / churning tail).  Best = lowest ITL p50
    — the gate is a latency ratio, so 'best' must mean least load
    noise on BOTH sides."""
    backend = lora_cfg.get("backend", "ragged")

    def best_of(env: dict) -> dict:
        best = None
        for _ in range(runs):
            line = run_bench(backend, dict(env))
            itl = line.get("itl_ms_p50")
            if itl is None:
                raise RuntimeError("bench emitted no itl_ms_p50")
            if best is None or itl < best["itl_ms_p50"]:
                best = line
        return best

    churn = best_of(lora_cfg.get("env", {}))
    single = best_of(lora_cfg.get("single_env", {}))
    print(
        f"perf_check: lora     churn itl_p50={churn['itl_ms_p50']}ms "
        f"single itl_p50={single['itl_ms_p50']}ms "
        f"resident_hw={churn.get('lora_resident_high_water')} "
        f"swaps_in={churn.get('lora_swaps_in')} "
        f"registered={churn.get('lora_adapters')}"
    )
    return churn, single


def measure_kv_tier(kv_cfg: dict, runs: int) -> dict:
    """Best-of-``runs`` prefix-reuse line (docs/KV_TIERING.md; the
    acceptance demo: device pool capped below the reusable working set,
    warm pass served through the host tier).  Best = lowest warm/cold
    TTFT ratio — the gate is a latency ratio, so 'best' must mean the
    least load-noise-polluted run."""
    backend = kv_cfg.get("backend", "ragged")
    best = None
    for _ in range(runs):
        line = run_bench(backend, dict(kv_cfg.get("env", {})))
        kv = line.get("kv_tier")
        if kv is None or kv.get("warm_cold_ttft_ratio") is None:
            raise RuntimeError("bench emitted no kv_tier stamps")
        if (
            best is None
            or kv["warm_cold_ttft_ratio"]
            < best["kv_tier"]["warm_cold_ttft_ratio"]
        ):
            best = line
    kv = best["kv_tier"]
    print(
        f"perf_check: kv_tier  warm/cold ttft "
        f"{kv['ttft_warm_ms_p50']}/{kv['ttft_cold_ms_p50']}ms "
        f"(ratio {kv['warm_cold_ttft_ratio']}) "
        f"hit_rate={kv['combined_hit_rate']} "
        f"host_tokens={kv['host_promoted_tokens']} "
        f"identical={kv['token_identical']}"
    )
    return best


def measure_disagg(dis_cfg: dict, runs: int) -> tuple[dict, dict]:
    """ISSUE 11 gate driver: the BENCH_ROLES chat+RAG scenario run
    twice — a disaggregated (prefill+decode) fleet and an all-mixed
    fleet at EQUAL replica count (docs/SCALING.md "Disaggregated
    roles").  Best of ``runs`` per mode = lowest chat ITL p99: the
    gate is a latency ratio, so 'best' must mean the least
    load-noise-polluted run on BOTH sides."""
    backend = dis_cfg.get("backend", "ragged")

    def best_of(mode: str) -> dict:
        best = None
        for _ in range(runs):
            env = dict(dis_cfg.get("env", {}))
            env["BENCH_ROLES"] = mode
            line = run_bench(backend, env)
            roles = line.get("roles")
            if not roles or roles.get("chat_itl_ms_p99") is None:
                raise RuntimeError(
                    f"bench ({mode}) emitted no roles stamps"
                )
            if (
                best is None
                or roles["chat_itl_ms_p99"]
                < best["roles"]["chat_itl_ms_p99"]
            ):
                best = line
        return best

    disagg = best_of("disagg")
    mixed = best_of("mixed")
    d, m = disagg["roles"], mixed["roles"]
    print(
        f"perf_check: disagg   chat itl_p99 {d['chat_itl_ms_p99']}ms "
        f"vs mixed {m['chat_itl_ms_p99']}ms at dp={d['dp']} "
        f"(handoffs {d['handoffs_completed']}/"
        f"{d['handoffs_fallback']} fallback) "
        f"identical={d['outputs_digest'] == m['outputs_digest']}"
    )
    return disagg, mixed


def measure_spec(spec_cfg: dict, runs: int) -> tuple[dict, dict]:
    """ISSUE 12 gate driver: the decode-heavy chat scenario under
    concurrent RAG prefill load (the BENCH_ROLES=mixed chat+RAG fleet),
    run with BENCH_SPEC=1 (same-weights draft — ragged verify spans)
    and BENCH_SPEC=0.  Best of ``runs`` per mode = lowest chat ITL p50:
    a latency-ratio gate, so 'best' must mean the least
    load-noise-polluted run on BOTH sides."""
    backend = spec_cfg.get("backend", "ragged")

    def best_of(spec_on: bool) -> dict:
        best = None
        for _ in range(runs):
            env = dict(spec_cfg.get("env", {}))
            env["BENCH_SPEC"] = "1" if spec_on else "0"
            env["BENCH_SPEC_GAMMA"] = str(spec_cfg.get("gamma", 4))
            line = run_bench(backend, env)
            roles = line.get("roles")
            if not roles or roles.get("chat_itl_ms_p50") is None:
                raise RuntimeError(
                    f"bench (spec={spec_on}) emitted no chat ITL stamps"
                )
            if (
                best is None
                or roles["chat_itl_ms_p50"]
                < best["roles"]["chat_itl_ms_p50"]
            ):
                best = line
        return best

    spec = best_of(True)
    plain = best_of(False)
    s = spec["roles"]
    print(
        f"perf_check: spec     chat itl_p50 {s['chat_itl_ms_p50']}ms vs "
        f"plain {plain['roles']['chat_itl_ms_p50']}ms "
        f"(acceptance {spec['spec']['acceptance_rate']}, "
        f"verify dispatches {spec['spec']['verify_dispatches']}) "
        f"identical="
        f"{s['outputs_digest'] == plain['roles']['outputs_digest']}"
    )
    return spec, plain


def measure_recovery(rec_cfg: dict, runs: int) -> dict:
    """ISSUE 10 gate driver: ``tools/chaos_soak.py --recovery-bench``
    in a subprocess (own engines, shared persistent XLA cache — see
    its docstring for the cold-vs-cold measurement discipline).  Best
    of ``runs`` = lowest ratio: a latency-ratio gate, so 'best' must
    mean the least load-noise-polluted run."""
    best = None
    for _ in range(max(1, runs)):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO_ROOT / "tools" / "chaos_soak.py"),
                "--recovery-bench",
            ],
            capture_output=True, text=True, timeout=600, env=env,
        )
        line = None
        for candidate in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(candidate)
            except ValueError:
                continue
            if isinstance(parsed, dict) and parsed.get("kind") == "recovery":
                line = parsed
                break
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"chaos_soak --recovery-bench failed "
                f"rc={proc.returncode}: {proc.stderr[-400:]}"
            )
        if best is None or line["ratio"] < best["ratio"]:
            best = line
    print(
        f"perf_check: recovery  resumed {best['resumed_s']}s vs "
        f"uncrashed {best['base_s']}s (ratio {best['ratio']}) "
        f"identical={best['token_identical']} resumed={best['resumed']}"
    )
    return best


def measure_quant(q_cfg: dict, runs: int) -> tuple[dict, dict | None]:
    """ISSUE 13 gate driver (docs/QUANTIZATION.md): the steady-state
    scenario suites (tools/scenarios.py --quant-gate) run bf16-KV vs
    --kv-quantization at an EQUAL synthetic HBM budget — per-scenario
    tok/s + logprob deltas + the analytic page-capacity ratio — plus
    the weight-only BENCH_QUANTIZATION bench line.  Best of ``runs`` =
    highest chat tok/s ratio (a ratio gate; the quality deltas are
    near-deterministic, so the same run serves them)."""
    scheme = q_cfg.get("scheme", "int8")
    best = None
    for _ in range(max(1, runs)):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO_ROOT / "tools" / "scenarios.py"),
                "--quant-gate", "--scheme", scheme,
            ],
            capture_output=True, text=True, timeout=900, env=env,
        )
        line = None
        for candidate in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(candidate)
            except ValueError:
                continue
            if isinstance(parsed, dict) and parsed.get("kind") == "quant":
                line = parsed
                break
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"scenarios --quant-gate failed rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"
            )
        if (
            best is None
            or line["scenarios"]["chat"]["tok_per_s_ratio"]
            > best["scenarios"]["chat"]["tok_per_s_ratio"]
        ):
            best = line
    weight_line = None
    weights_env = q_cfg.get("weights_env")
    if weights_env:
        for _ in range(max(1, runs)):
            line = run_bench(
                q_cfg.get("backend", "ragged"), dict(weights_env)
            )
            if weight_line is None or line["value"] > weight_line["value"]:
                weight_line = line
    cap = best["capacity"]
    chat = best["scenarios"]["chat"]
    print(
        f"perf_check: quant    {scheme} capacity "
        f"{cap['bf16_blocks']}→{cap['quant_blocks']} pages "
        f"({cap['ratio']}x), chat tok/s "
        f"{chat['bf16_tok_per_s']}→{chat['quant_tok_per_s']} "
        f"({chat['tok_per_s_ratio']}x), logprob deltas "
        + ", ".join(
            f"{s}={line['mean_abs_logprob_delta']}"
            for s, line in best["scenarios"].items()
        )
        + (
            f", weights {weight_line['value']:.1f} tok/s "
            f"@ {weight_line['weight_resident_bytes']}B"
            if weight_line is not None
            else ""
        )
    )
    return best, weight_line


def measure_cross_host(x_cfg: dict, runs: int) -> dict:
    """ISSUE 19 gate driver: ``tools/scenarios.py --cross-host-gate``
    in a subprocess — the same prefill→decode request over the
    in-process dp=2 handoff vs a loopback-TCP kvnet handoff, plus the
    remote-prefix-fetch leg (docs/CROSS_HOST.md).  Best of ``runs`` =
    lowest overhead ratio: a latency-ratio gate, so 'best' must mean
    the least load-noise-polluted run."""
    best = None
    for _ in range(max(1, runs)):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO_ROOT / "tools" / "scenarios.py"),
                "--cross-host-gate",
            ],
            capture_output=True, text=True, timeout=600, env=env,
        )
        line = None
        for candidate in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(candidate)
            except ValueError:
                continue
            if (
                isinstance(parsed, dict)
                and parsed.get("kind") == "cross_host"
            ):
                line = parsed
                break
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"scenarios --cross-host-gate failed "
                f"rc={proc.returncode}: {proc.stderr[-400:]}"
            )
        if best is None or line["overhead_ratio"] < best["overhead_ratio"]:
            best = line
    print(
        f"perf_check: cross_host remote handoff "
        f"{best['remote']['wall_s']}s vs local "
        f"{best['local']['wall_s']}s (ratio {best['overhead_ratio']}) "
        f"prefix_hits={best['remote_prefix']['hits']} "
        f"identical={best['token_identical']}"
    )
    return best


def measure_unified(u_cfg: dict, runs: int) -> dict:
    """ISSUE 14 gate driver (docs/MEMORY.md): the unified-arena tiered
    memory measurement (tools/scenarios.py --unified-gate) — a mixed
    RAG + adapter-churn working set >= 4x the device pool served
    through arena + host tier + disk tier; cold pass populates, warm
    pass must hit.  Best of ``runs`` = lowest warm/cold TTFT ratio."""
    best = None
    for _ in range(max(1, runs)):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                sys.executable,
                str(REPO_ROOT / "tools" / "scenarios.py"),
                "--unified-gate",
            ],
            capture_output=True, text=True, timeout=900, env=env,
        )
        line = None
        for candidate in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(candidate)
            except ValueError:
                continue
            if isinstance(parsed, dict) and parsed.get("kind") == "unified":
                line = parsed
                break
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"scenarios --unified-gate failed rc={proc.returncode}: "
                f"{proc.stderr[-400:]}"
            )
        if (
            best is None
            or line["warm_cold_ratio"] < best["warm_cold_ratio"]
        ):
            best = line
    print(
        f"perf_check: unified  working set "
        f"{best['working_set_ratio']}x HBM, warm TTFT "
        f"{best['ttft_ms_p50_warm']}ms vs cold "
        f"{best['ttft_ms_p50_cold']}ms ({best['warm_cold_ratio']}x), "
        f"{best['completed']}/{best['offered']} completed, disk "
        f"{best['tier']['disk']['stored_pages']} stored / "
        f"{best['tier']['disk']['loaded_pages']} loaded, arena "
        f"charges {best['arena']['adapter_charges']}"
    )
    return best


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    write = "--write" in argv
    try:
        baseline = json.loads(BASELINE_PATH.read_text())
    except FileNotFoundError:
        if not write:
            print(f"perf_check: {BASELINE_PATH} missing — run --write first")
            return 2
        baseline = {"backends": {}}
    runs = int(baseline.get("runs", 2))
    tolerance = float(baseline.get("tolerance", 0.20))
    waste_slack = float(baseline.get("waste_slack", 0.05))
    env_overrides = dict(baseline.get("env", {}))

    measured: dict[str, dict] = {}
    for backend in ("ragged",):
        try:
            measured[backend] = measure(backend, runs, env_overrides)
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: measurement failed for {backend}: {exc}")
            return 2
        m = measured[backend]
        print(
            f"perf_check: {backend:8s} "
            f"tok/s={m['aggregate_output_tok_per_s']:8.1f} "
            f"waste={m['padding_waste_frac']:.4f} "
            f"shapes={m['compiled_shapes']}"
        )

    dp_cfg = baseline.get("dp")
    dp_measured: dict[str, dict] = {}
    if dp_cfg:
        try:
            dp_measured = measure_dp(dp_cfg, int(dp_cfg.get("runs", runs)))
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: dp measurement failed: {exc}")
            return 2

    lora_cfg = baseline.get("lora")
    lora_churn: dict | None = None
    lora_single: dict | None = None
    if lora_cfg:
        try:
            lora_churn, lora_single = measure_lora(
                lora_cfg, int(lora_cfg.get("runs", runs))
            )
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: lora measurement failed: {exc}")
            return 2

    kv_cfg = baseline.get("kv_tier")
    kv_line: dict | None = None
    if kv_cfg:
        try:
            kv_line = measure_kv_tier(kv_cfg, int(kv_cfg.get("runs", runs)))
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: kv_tier measurement failed: {exc}")
            return 2

    dis_cfg = baseline.get("disagg")
    dis_line: dict | None = None
    mixed_line: dict | None = None
    if dis_cfg:
        try:
            dis_line, mixed_line = measure_disagg(
                dis_cfg, int(dis_cfg.get("runs", runs))
            )
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: disagg measurement failed: {exc}")
            return 2

    spec_cfg = baseline.get("spec")
    spec_line: dict | None = None
    plain_line: dict | None = None
    if spec_cfg:
        try:
            spec_line, plain_line = measure_spec(
                spec_cfg, int(spec_cfg.get("runs", runs))
            )
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: spec measurement failed: {exc}")
            return 2

    rec_cfg = baseline.get("recovery")
    rec_line: dict | None = None
    if rec_cfg:
        try:
            rec_line = measure_recovery(
                rec_cfg, int(rec_cfg.get("runs", 1))
            )
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: recovery measurement failed: {exc}")
            return 2

    q_cfg = baseline.get("quant")
    q_line: dict | None = None
    q_weight_line: dict | None = None
    if q_cfg:
        try:
            q_line, q_weight_line = measure_quant(
                q_cfg, int(q_cfg.get("runs", 1))
            )
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: quant measurement failed: {exc}")
            return 2

    u_cfg = baseline.get("unified")
    u_line: dict | None = None
    if u_cfg:
        try:
            u_line = measure_unified(u_cfg, int(u_cfg.get("runs", 1)))
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: unified measurement failed: {exc}")
            return 2

    x_cfg = baseline.get("cross_host")
    x_line: dict | None = None
    if x_cfg:
        try:
            x_line = measure_cross_host(x_cfg, int(x_cfg.get("runs", 1)))
        except Exception as exc:  # noqa: BLE001 — tool boundary
            print(f"perf_check: cross_host measurement failed: {exc}")
            return 2

    if write:
        out = {
            "_comment": (
                "CPU-proxy perf floors for nox -s perf_check (best of "
                "`runs` BENCH_TINY passes per backend; see "
                "tools/perf_check.py and BASELINE.md 'Perf regression "
                "log').  Update with `python tools/perf_check.py "
                "--write` after a reviewed perf-relevant change."
            ),
            "runs": runs,
            "tolerance": tolerance,
            "waste_slack": waste_slack,
            "env": env_overrides,
            "backends": {
                name: {
                    "aggregate_output_tok_per_s": round(
                        m["aggregate_output_tok_per_s"], 1
                    ),
                    "padding_waste_frac": round(
                        m["padding_waste_frac"], 4
                    ),
                }
                for name, m in measured.items()
            },
        }
        if lora_cfg:
            # the lora section is declarative (ratio + structural
            # demands, not measured floors) — carried through, with the
            # tok/s floor refreshed at the documented ~70% haircut
            out["lora"] = {
                **lora_cfg,
                **(
                    {"min_tok_per_s": round(lora_churn["value"] * 0.7, 1)}
                    if lora_churn is not None
                    else {}
                ),
            }
        if kv_cfg:
            # declarative section (ratio + structural demands): carried
            # through unchanged — there is no measured floor to refresh
            out["kv_tier"] = dict(kv_cfg)
        if rec_cfg:
            # declarative too: the ≤2x resumed/uncrashed ratio is the
            # ISSUE 10 acceptance bound, not a measured floor
            out["recovery"] = dict(rec_cfg)
        if dis_cfg:
            # declarative (ratio + structural demands): the ≤0.5x
            # disagg/mixed chat-ITL bound is the ISSUE 11 acceptance
            # criterion, not a measured floor
            out["disagg"] = dict(dis_cfg)
        if spec_cfg:
            # declarative: the ≥1.5x spec/plain chat-ITL speedup and
            # ≥0.6 acceptance are the ISSUE 12 acceptance criteria
            out["spec"] = dict(spec_cfg)
        if q_cfg:
            # declarative (capacity/speedup/quality bounds are the
            # ISSUE 13 acceptance criteria); only the weight-path
            # tok/s floor is measured, refreshed at the ~70% haircut
            out["quant"] = {
                **q_cfg,
                **(
                    {"min_weight_tok_per_s": round(
                        q_weight_line["value"] * 0.7, 1
                    )}
                    if q_weight_line is not None
                    else {}
                ),
            }
        if u_cfg:
            # declarative: the <=0.5x warm/cold bound, the >=4x working
            # set, and the zero-deadlock completion demand are the
            # ISSUE 14 acceptance criteria, not measured floors
            out["unified"] = dict(u_cfg)
        if x_cfg:
            # declarative: the remote-vs-local handoff overhead bound
            # and the structural remote-hit/handoff demands are the
            # ISSUE 19 acceptance criteria, not measured floors
            out["cross_host"] = dict(x_cfg)
        if dp_cfg:
            out["dp"] = {
                **dp_cfg,
                # the dp gate compares floors with NO additional
                # tolerance (unlike the main tok/s gate), so the ~70%
                # haircut the checked-in style documents is applied at
                # write time — a freshly written baseline must not fail
                # the very next run on ordinary best-of-N jitter
                "floors_tok_per_s": {
                    point: round(line["value"] * 0.7, 1)
                    for point, line in dp_measured.items()
                },
            }
        BASELINE_PATH.write_text(json.dumps(out, indent=2) + "\n")
        print(f"perf_check: baseline written to {BASELINE_PATH}")
        return 0

    failures = []
    for backend, base in baseline.get("backends", {}).items():
        m = measured.get(backend)
        if m is None:
            failures.append(f"{backend}: no measurement")
            continue
        floor = base["aggregate_output_tok_per_s"] * (1.0 - tolerance)
        if m["aggregate_output_tok_per_s"] < floor:
            failures.append(
                f"{backend}: {m['aggregate_output_tok_per_s']:.1f} tok/s "
                f"< floor {floor:.1f} (baseline "
                f"{base['aggregate_output_tok_per_s']:.1f} - {tolerance:.0%})"
            )
        waste_ceiling = base["padding_waste_frac"] + waste_slack
        if m["padding_waste_frac"] > waste_ceiling:
            failures.append(
                f"{backend}: padding waste {m['padding_waste_frac']:.4f} "
                f"> ceiling {waste_ceiling:.4f} (baseline "
                f"{base['padding_waste_frac']:.4f} + {waste_slack})"
            )

    if dp_cfg:
        # absolute floors (already hand-haircut in the checked-in file,
        # so compared directly — no extra tolerance)
        for point, floor in dp_cfg.get("floors_tok_per_s", {}).items():
            line = dp_measured.get(str(point))
            if line is None:
                failures.append(f"dp={point}: no measurement")
            elif line["value"] < floor:
                failures.append(
                    f"dp={point}: {line['value']:.1f} tok/s < floor "
                    f"{floor:.1f}"
                )
        # near-linear scaling vs the SAME session's dp=1 measurement
        base_line = dp_measured.get("1")
        for point, min_ratio in dp_cfg.get("min_scaling", {}).items():
            line = dp_measured.get(str(point))
            if line is None or base_line is None:
                failures.append(f"dp={point}: scaling unmeasurable")
                continue
            ratio = line["value"] / max(base_line["value"], 1e-9)
            if ratio < min_ratio:
                failures.append(
                    f"dp={point}: {ratio:.2f}x dp=1 < required "
                    f"{min_ratio}x ({line['value']:.1f} vs "
                    f"{base_line['value']:.1f} tok/s)"
                )

    if lora_cfg and lora_churn is not None and lora_single is not None:
        # ISSUE 8 acceptance: adapter-churn ITL within max_itl_ratio of
        # the single-adapter run (same session, so load jitter cancels),
        # the demo residency/churn shape actually achieved, and a
        # conservative absolute tok/s floor
        ratio = lora_churn["itl_ms_p50"] / max(
            lora_single["itl_ms_p50"], 1e-9
        )
        max_ratio = float(lora_cfg.get("max_itl_ratio", 1.5))
        if ratio > max_ratio:
            failures.append(
                f"lora: churn ITL p50 {lora_churn['itl_ms_p50']}ms is "
                f"{ratio:.2f}x the single-adapter run "
                f"({lora_single['itl_ms_p50']}ms) > allowed {max_ratio}x"
            )
        min_resident = int(lora_cfg.get("min_resident", 0))
        if lora_churn.get("lora_resident_high_water", 0) < min_resident:
            failures.append(
                f"lora: resident high-water "
                f"{lora_churn.get('lora_resident_high_water')} < "
                f"required {min_resident} (pool not actually exercised)"
            )
        min_swaps = int(lora_cfg.get("min_swaps_in", 0))
        if lora_churn.get("lora_swaps_in", 0) < min_swaps:
            failures.append(
                f"lora: swaps_in {lora_churn.get('lora_swaps_in')} < "
                f"required {min_swaps} (no churn happened)"
            )
        floor = float(lora_cfg.get("min_tok_per_s", 0.0))
        if lora_churn["value"] < floor:
            failures.append(
                f"lora: {lora_churn['value']:.1f} tok/s < floor {floor:.1f}"
            )

    if kv_cfg and kv_line is not None:
        # ISSUE 9 acceptance: with the device prefix pool capped below
        # the reusable working set, warm TTFT p50 ≤ max_warm_ttft_ratio
        # of cold, combined hit rate ≥ min_hit_rate, the host tier
        # actually served tokens, and cold↔warm outputs token-identical
        kv = kv_line["kv_tier"]
        max_ratio = float(kv_cfg.get("max_warm_ttft_ratio", 0.6))
        if kv["warm_cold_ttft_ratio"] > max_ratio:
            failures.append(
                f"kv_tier: warm TTFT p50 {kv['ttft_warm_ms_p50']}ms is "
                f"{kv['warm_cold_ttft_ratio']}x cold "
                f"({kv['ttft_cold_ms_p50']}ms) > allowed {max_ratio}x"
            )
        min_hit = float(kv_cfg.get("min_hit_rate", 0.5))
        if kv["combined_hit_rate"] < min_hit:
            failures.append(
                f"kv_tier: combined hit rate {kv['combined_hit_rate']} "
                f"< required {min_hit}"
            )
        min_host = int(kv_cfg.get("min_host_promoted_tokens", 0))
        if kv.get("host_promoted_tokens", 0) < min_host:
            failures.append(
                f"kv_tier: host_promoted_tokens "
                f"{kv.get('host_promoted_tokens')} < required {min_host} "
                "(reuse never flowed through the host tier)"
            )
        if not kv.get("token_identical"):
            failures.append(
                "kv_tier: warm-pass outputs diverged from the cold pass "
                "(promoted KV must be byte-equivalent to recompute)"
            )

    if dis_cfg and dis_line is not None and mixed_line is not None:
        # ISSUE 11 acceptance: chat ITL p99 under concurrent RAG load
        # ≥ 2x better disaggregated than all-mixed at equal replica
        # count, handoff streams token-identical (same greedy outputs
        # digest), every handoff actually taken (none fell back)
        d, m = dis_line["roles"], mixed_line["roles"]
        max_ratio = float(dis_cfg.get("max_itl_ratio", 0.5))
        ratio = d["chat_itl_ms_p99"] / max(m["chat_itl_ms_p99"], 1e-9)
        if ratio > max_ratio:
            failures.append(
                f"disagg: chat ITL p99 {d['chat_itl_ms_p99']}ms is "
                f"{ratio:.2f}x the mixed fleet's "
                f"({m['chat_itl_ms_p99']}ms) > allowed {max_ratio}x — "
                "disaggregation stopped isolating chat from RAG "
                "prefill"
            )
        if d["outputs_digest"] != m["outputs_digest"]:
            failures.append(
                "disagg: outputs digest diverged from the mixed fleet "
                "(handoff must be token-identical)"
            )
        min_handoffs = int(dis_cfg.get("min_handoffs", 1))
        if d.get("handoffs_completed", 0) < min_handoffs:
            failures.append(
                f"disagg: {d.get('handoffs_completed')} handoffs "
                f"completed < required {min_handoffs} (the split fleet "
                "did not actually hand off)"
            )
        if d.get("handoffs_fallback", 0) > 0:
            failures.append(
                f"disagg: {d['handoffs_fallback']} handoff(s) fell "
                "back to retryable failure under a healthy fleet"
            )
        if m.get("handoffs_completed", 0) != 0:
            failures.append(
                "disagg: the mixed-mode control run handed off "
                f"{m['handoffs_completed']} request(s) — control is "
                "contaminated"
            )

    if spec_cfg and spec_line is not None and plain_line is not None:
        # ISSUE 12 acceptance: ragged+spec beats plain ragged by >=
        # min_itl_speedup on decode-heavy chat ITL at acceptance >=
        # min_acceptance, token-identical under greedy, with verify
        # dispatches actually taken
        s, pl = spec_line["roles"], plain_line["roles"]
        st = spec_line.get("spec", {})
        min_speedup = float(spec_cfg.get("min_itl_speedup", 1.5))
        speedup = pl["chat_itl_ms_p50"] / max(s["chat_itl_ms_p50"], 1e-9)
        if speedup < min_speedup:
            failures.append(
                f"spec: chat ITL p50 {s['chat_itl_ms_p50']}ms is only "
                f"{speedup:.2f}x better than plain ragged "
                f"({pl['chat_itl_ms_p50']}ms) < required {min_speedup}x"
            )
        min_accept = float(spec_cfg.get("min_acceptance", 0.6))
        if st.get("acceptance_rate", 0.0) < min_accept:
            failures.append(
                f"spec: acceptance {st.get('acceptance_rate')} < "
                f"required {min_accept} (draft/verify machinery broken "
                "— the same-weights draft should accept ~everything)"
            )
        min_vd = int(spec_cfg.get("min_verify_dispatches", 1))
        if st.get("verify_dispatches", 0) < min_vd:
            failures.append(
                f"spec: {st.get('verify_dispatches')} verify dispatches "
                f"< required {min_vd} (speculation never actually ran)"
            )
        if s["outputs_digest"] != pl["outputs_digest"]:
            failures.append(
                "spec: outputs digest diverged from the plain ragged "
                "run (verify spans must be token-identical under "
                "greedy sampling)"
            )

    if rec_cfg and rec_line is not None:
        # ISSUE 10 acceptance: a request killed mid-decode completes
        # RESUMED within max_ratio x its uncrashed wall time, with the
        # resumed stream token-identical and the resume actually taken
        # (not the fallback ladder)
        max_ratio = float(rec_cfg.get("max_ratio", 2.0))
        if rec_line["ratio"] > max_ratio:
            failures.append(
                f"recovery: resumed completion {rec_line['resumed_s']}s "
                f"is {rec_line['ratio']}x the uncrashed baseline "
                f"({rec_line['base_s']}s) > allowed {max_ratio}x"
            )
        if not rec_line.get("token_identical"):
            failures.append(
                "recovery: resumed stream diverged from the uncrashed "
                "baseline (checkpoint/resume must be token-identical)"
            )
        if rec_line.get("resumed", 0) < 1:
            failures.append(
                "recovery: the mid-decode request was not resumed "
                "(fallback ladder taken — gate measured nothing)"
            )

    if q_cfg and q_line is not None:
        # ISSUE 13 acceptance (docs/QUANTIZATION.md): KV-page capacity
        # ≥ min_capacity_ratio x bf16 at equal HBM, per-scenario
        # logprob deltas bounded (token quality IS the gate — greedy
        # identity cannot police a numerics-changing optimization),
        # chat-suite tok/s ≥ min_chat_speedup with the device pool
        # capped below the working set, and the weight-only int8 path
        # floored with its resident-bytes saving demonstrated
        cap_ratio = q_line["capacity"]["ratio"]
        min_cap = float(q_cfg.get("min_capacity_ratio", 1.9))
        if cap_ratio < min_cap:
            failures.append(
                f"quant: KV-page capacity {cap_ratio}x bf16 at equal "
                f"HBM < required {min_cap}x "
                f"({q_line['capacity']['bf16_blocks']} → "
                f"{q_line['capacity']['quant_blocks']} pages)"
            )
        max_deltas = q_cfg.get("max_logprob_delta", {})
        min_match = q_cfg.get("min_token_match", {})
        for suite, line in q_line["scenarios"].items():
            bound = float(max_deltas.get(suite, 0.05))
            delta = line.get("mean_abs_logprob_delta")
            if delta is None:
                failures.append(
                    f"quant/{suite}: no logprob deltas measured "
                    "(quality gate measured nothing)"
                )
            elif delta > bound:
                failures.append(
                    f"quant/{suite}: mean |Δlogprob| {delta} > bound "
                    f"{bound} (quantized KV is perturbing token "
                    "quality beyond the per-scenario budget)"
                )
            floor = float(
                min_match.get(suite, 0.3)
                if isinstance(min_match, dict)
                else min_match
            )
            if line.get("token_match_frac", 0.0) < floor:
                failures.append(
                    f"quant/{suite}: token_match_frac "
                    f"{line.get('token_match_frac')} < required {floor}"
                )
        chat = q_line["scenarios"]["chat"]
        min_speed = float(q_cfg.get("min_chat_speedup", 1.3))
        if chat["tok_per_s_ratio"] < min_speed:
            failures.append(
                f"quant: chat-suite tok/s ratio "
                f"{chat['tok_per_s_ratio']}x bf16 < required "
                f"{min_speed}x at equal HBM "
                f"({chat['bf16_tok_per_s']} vs "
                f"{chat['quant_tok_per_s']} tok/s — the 2x page pool "
                "stopped buying batch occupancy)"
            )
        if q_weight_line is not None:
            floor = float(q_cfg.get("min_weight_tok_per_s", 0.0))
            if q_weight_line["value"] < floor:
                failures.append(
                    f"quant/weights: {q_weight_line['value']:.1f} "
                    f"tok/s < floor {floor:.1f}"
                )
            base_bytes = (
                measured.get("ragged", {}).get("weight_resident_bytes")
            )
            max_ratio = float(q_cfg.get("max_weight_bytes_ratio", 0.75))
            if base_bytes:
                ratio = (
                    q_weight_line["weight_resident_bytes"] / base_bytes
                )
                if ratio > max_ratio:
                    failures.append(
                        f"quant/weights: resident bytes "
                        f"{q_weight_line['weight_resident_bytes']} are "
                        f"{ratio:.2f}x the full-precision run "
                        f"({base_bytes}) > allowed {max_ratio}x — "
                        "int8 weight quantization stopped saving HBM"
                    )

    if u_cfg and u_line is not None:
        # ISSUE 14 acceptance: mixed RAG + adapter-churn working set
        # >= min_working_set_ratio x the device pool sustains warm-hit
        # TTFT <= max_warm_cold_ratio x cold, with zero allocation
        # deadlocks (every offered request completed) and the full
        # hierarchy demonstrably exercised (host evictions cascaded to
        # the disk tier, disk promotions served, arena charges flowed)
        max_ratio = float(u_cfg.get("max_warm_cold_ratio", 0.5))
        if u_line["warm_cold_ratio"] > max_ratio:
            failures.append(
                f"unified: warm TTFT p50 {u_line['ttft_ms_p50_warm']}ms "
                f"is {u_line['warm_cold_ratio']}x cold "
                f"({u_line['ttft_ms_p50_cold']}ms) > allowed {max_ratio}x"
            )
        min_ws = float(u_cfg.get("min_working_set_ratio", 4.0))
        if u_line["working_set_ratio"] < min_ws:
            failures.append(
                f"unified: working set {u_line['working_set_ratio']}x "
                f"the device pool < required {min_ws}x (the gate "
                "stopped oversubscribing HBM)"
            )
        if u_line["completed"] != u_line["offered"]:
            failures.append(
                f"unified: {u_line['completed']}/{u_line['offered']} "
                "requests completed — an allocation deadlock (or shed) "
                "under arena pressure"
            )
        disk = u_line["tier"]["disk"] or {}
        if not disk.get("stored_pages"):
            failures.append(
                "unified: the disk tier stored nothing — host "
                "evictions stopped cascading down the hierarchy"
            )
        if not disk.get("loaded_pages"):
            failures.append(
                "unified: the disk tier served nothing — promotions "
                "never walked disk→host→device"
            )
        if not (u_line.get("arena") or {}).get("adapter_charges"):
            failures.append(
                "unified: the arena charged no adapters — the unified "
                "budget was not exercised"
            )

    if x_cfg and x_line is not None:
        # ISSUE 19 acceptance: a loopback-TCP kvnet handoff completes
        # within max_overhead_ratio x the in-process dp=2 handoff,
        # token-identical across all three legs, with the remote path
        # actually taken (kvnet handoffs counted) and the
        # remote-prefix leg actually served over the wire
        max_ratio = float(x_cfg.get("max_overhead_ratio", 2.5))
        if x_line["overhead_ratio"] > max_ratio:
            failures.append(
                f"cross_host: remote handoff {x_line['remote']['wall_s']}s "
                f"is {x_line['overhead_ratio']}x the local fleet's "
                f"({x_line['local']['wall_s']}s) > allowed {max_ratio}x"
            )
        if not x_line.get("token_identical"):
            failures.append(
                "cross_host: remote handoff or remote-prefix outputs "
                "diverged (a remote hit must behave exactly like a "
                "local one)"
            )
        min_handoffs = int(x_cfg.get("min_remote_handoffs", 1))
        if x_line["remote"].get("handoffs_remote", 0) < min_handoffs:
            failures.append(
                f"cross_host: {x_line['remote'].get('handoffs_remote')} "
                f"kvnet handoffs < required {min_handoffs} (the remote "
                "path was not actually taken)"
            )
        min_hits = int(x_cfg.get("min_remote_prefix_hits", 1))
        if x_line["remote_prefix"].get("hits", 0) < min_hits:
            failures.append(
                f"cross_host: {x_line['remote_prefix'].get('hits')} "
                f"remote prefix pages served < required {min_hits} "
                "(the prefix-sharing path was not actually exercised)"
            )

    if failures:
        print("perf_check: REGRESSION")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("perf_check: green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
