"""Logger configuration for the whole framework.

The reference clones vLLM's dictConfig for its own namespace
(reference: logging.py:10-22).  We own the whole stack here, so we define the
format directly: one concise line per record with timestamp, level, and
location, matching the operational style of the reference's logs.
"""

from __future__ import annotations

import logging
import logging.config
import os
import sys

DEFAULT_LOGGER_NAME = __name__.split(".")[0]

_FORMAT = (
    "%(levelname)s %(asctime)s.%(msecs)03d %(filename)s:%(lineno)d] %(message)s"
)
_DATE_FORMAT = "%m-%d %H:%M:%S"

_LOGGING_CONFIG = {
    "version": 1,
    "disable_existing_loggers": False,
    "formatters": {
        DEFAULT_LOGGER_NAME: {
            "format": _FORMAT,
            "datefmt": _DATE_FORMAT,
        },
    },
    "handlers": {
        DEFAULT_LOGGER_NAME: {
            "class": "logging.StreamHandler",
            "formatter": DEFAULT_LOGGER_NAME,
            "level": os.getenv("TGIS_TPU_LOG_LEVEL", "INFO").upper(),
            "stream": "ext://sys.stdout",
        },
    },
    "loggers": {
        DEFAULT_LOGGER_NAME: {
            "handlers": [DEFAULT_LOGGER_NAME],
            "level": "DEBUG",
            "propagate": False,
        },
    },
}

_configured = False


def _configure() -> None:
    global _configured
    if not _configured:
        logging.config.dictConfig(_LOGGING_CONFIG)
        _configured = True


def init_logger(name: str) -> logging.Logger:
    """Return a logger under the framework's root logger namespace."""
    _configure()
    if name == DEFAULT_LOGGER_NAME or name.startswith(DEFAULT_LOGGER_NAME + "."):
        return logging.getLogger(name)
    return logging.getLogger(f"{DEFAULT_LOGGER_NAME}.{name}")
