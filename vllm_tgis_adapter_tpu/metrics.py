"""Prometheus metrics for the serving layer.

The reference exposes engine metrics through vLLM's HTTP ``/metrics``
endpoint (pyproject.toml:31, exercised by tests/test_http_server.py:32-35).
Here the registry is fed directly by our engine and servers.
"""

from __future__ import annotations

from prometheus_client import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_PREFIX = "tgis_tpu"


def _get_or_create(cls, name: str, doc: str, **kwargs):  # noqa: ANN001, ANN003, ANN202
    """Idempotent metric construction (tests boot multiple servers)."""
    try:
        return cls(name, doc, **kwargs)
    except ValueError:
        collector = REGISTRY._names_to_collectors.get(name)  # noqa: SLF001
        if collector is None:
            raise
        return collector


request_count = _get_or_create(
    Counter,
    f"{_PREFIX}_request_count",
    "Total generation requests processed",
    labelnames=("kind",),
)
request_failure_count = _get_or_create(
    Counter,
    f"{_PREFIX}_request_failure_count",
    "Total failed generation requests",
)
prompt_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_prompt_tokens_total",
    "Total prompt tokens processed",
)
generated_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_generated_tokens_total",
    "Total tokens generated",
)
request_duration = _get_or_create(
    Histogram,
    f"{_PREFIX}_request_duration_seconds",
    "End-to-end request duration",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
queue_duration = _get_or_create(
    Histogram,
    f"{_PREFIX}_queue_duration_seconds",
    "Time requests spend queued before first schedule",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
num_requests_running = _get_or_create(
    Gauge,
    f"{_PREFIX}_num_requests_running",
    "Requests currently being generated",
)
spec_proposed_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_spec_proposed_tokens_total",
    "Draft tokens proposed by speculative decoding",
)
spec_accepted_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_spec_accepted_tokens_total",
    "Draft tokens accepted by target verification",
)

# ---- engine-state gauges (k8s autoscaling keys off exactly these; the
# reference exports the vLLM equivalents vllm:num_requests_running/
# waiting/gpu_cache_usage_perc through its /metrics).  Fed by the async
# engine's stats loop (engine/async_llm.py), aggregated over dp replicas.
num_requests_waiting = _get_or_create(
    Gauge,
    f"{_PREFIX}_num_requests_waiting",
    "Requests queued, not yet running",
)
kv_pages_total = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_pages_total",
    "KV cache pages in the pool (all replicas)",
)
kv_pages_used = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_pages_used",
    "KV cache pages currently allocated",
)
kv_cache_usage = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_cache_usage",
    "Fraction of KV cache pages in use (0-1)",
)
prefix_cache_hit_tokens = _get_or_create(
    Gauge,
    f"{_PREFIX}_prefix_cache_hit_tokens",
    "Cumulative prompt tokens served from the prefix cache",
)


# ---- --swap-space host KV swap (engine/core.py): preemption victims'
# pages copied to host and restored on re-admission instead of
# recompute-prefill
kv_swap_out_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_swap_out_total",
    "Preempted sequences whose KV pages were swapped to host memory",
)
kv_swap_in_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_swap_in_total",
    "Sequences restored from host KV swap instead of recompute-prefill",
)
kv_swap_used_bytes = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_swap_used_bytes",
    "Host bytes currently held by swapped-out KV copies",
)


# ---- guided-decoding constraint compilation (engine/constrained.py
# compile_fsm): first use of a constraint compiles a DFA + token table
# synchronously; repeats hit the LRU.  These expose the latency spike
# and the hit rate.
constraint_cache_hits = _get_or_create(
    Counter,
    f"{_PREFIX}_constraint_cache_hits",
    "Guided-decoding constraints served from the compiled-FSM cache",
)
constraint_cache_misses = _get_or_create(
    Counter,
    f"{_PREFIX}_constraint_cache_misses",
    "Guided-decoding constraints that required a fresh FSM compilation",
)
constraint_compile_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_constraint_compile_seconds",
    "Wall time of guided-decoding FSM compilation (DFA + token table)",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)


# ---- MoE capacity-dispatch observability (judge r4 weak #5): capacity
# routing drops over-capacity assignments SILENTLY inside the jitted
# forward; these make the accuracy/throughput trade visible.  Fed from
# the model via io_callback on single-device engines (models/llama.py
# _moe_capacity_mlp; gated off under SPMD meshes where host callbacks
# would serialize the collective schedule).
moe_dropped_assignments_total = _get_or_create(
    Counter,
    f"{_PREFIX}_moe_dropped_assignments_total",
    "MoE (token, expert) assignments dropped for exceeding expert "
    "capacity under --moe-dispatch capacity",
)
moe_assignments_total = _get_or_create(
    Counter,
    f"{_PREFIX}_moe_assignments_total",
    "Total MoE (token, expert) assignments routed under capacity dispatch",
)
moe_expert_capacity = _get_or_create(
    Gauge,
    f"{_PREFIX}_moe_expert_capacity",
    "Realized per-expert buffer rows of the most recent MoE dispatch "
    "(ceil(T*k/E * capacity_factor), bounded by T)",
)


def record_moe_dispatch(dropped: int, total: int, capacity: int) -> None:
    moe_dropped_assignments_total.inc(int(dropped))
    moe_assignments_total.inc(int(total))
    moe_expert_capacity.set(int(capacity))


def update_engine_gauges(
    *,
    waiting: int,
    kv_used: int,
    kv_total: int,
    prefix_hits: int,
) -> None:
    # num_requests_running is NOT set here: the serving layer inc/decs it
    # per request (tgis_utils/logs.py) and a periodic .set() from a
    # second writer would flip-flop the two views
    num_requests_waiting.set(waiting)
    kv_pages_used.set(kv_used)
    kv_pages_total.set(kv_total)
    kv_cache_usage.set(kv_used / kv_total if kv_total else 0.0)
    prefix_cache_hit_tokens.set(prefix_hits)


def record_response(
    *,
    kind: str,
    prompt_tokens: int,
    generated_tokens: int,
    duration_s: float,
    queue_s: float,
) -> None:
    request_count.labels(kind=kind).inc()
    prompt_tokens_total.inc(prompt_tokens)
    generated_tokens_total.inc(generated_tokens)
    request_duration.observe(duration_s)
    queue_duration.observe(queue_s)


def render() -> bytes:
    return generate_latest(REGISTRY)
