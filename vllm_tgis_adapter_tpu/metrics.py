"""Prometheus metrics for the serving layer.

The reference exposes engine metrics through vLLM's HTTP ``/metrics``
endpoint (pyproject.toml:31, exercised by tests/test_http_server.py:32-35).
Here the registry is fed directly by our engine and servers.
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

from prometheus_client import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_PREFIX = "tgis_tpu"

_C = TypeVar("_C")

# every collector this module ever constructed, keyed by metric name — the
# idempotency source of truth, so re-registration never has to reach into
# prometheus_client's private registry internals
_COLLECTORS: dict[str, Any] = {}


def _get_or_create(
    cls: Callable[..., _C], name: str, doc: str, **kwargs: Any
) -> _C:
    """Idempotent metric construction (tests boot multiple servers)."""
    collector = _COLLECTORS.get(name)
    if collector is None:
        collector = cls(name, doc, **kwargs)
        _COLLECTORS[name] = collector
    return collector


request_count = _get_or_create(
    Counter,
    f"{_PREFIX}_request_count",
    "Total generation requests processed",
    labelnames=("kind",),
)
request_failure_count = _get_or_create(
    Counter,
    f"{_PREFIX}_request_failure_count",
    "Total failed generation requests",
)
prompt_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_prompt_tokens_total",
    "Total prompt tokens processed",
)
generated_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_generated_tokens_total",
    "Total tokens generated",
)
request_duration = _get_or_create(
    Histogram,
    f"{_PREFIX}_request_duration_seconds",
    "End-to-end request duration",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
queue_duration = _get_or_create(
    Histogram,
    f"{_PREFIX}_queue_duration_seconds",
    "Time requests spend queued before first schedule",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
num_requests_running = _get_or_create(
    Gauge,
    f"{_PREFIX}_num_requests_running",
    "Requests currently being generated",
)
spec_proposed_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_spec_proposed_tokens_total",
    "Draft tokens proposed by speculative decoding",
)
spec_accepted_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_spec_accepted_tokens_total",
    "Draft tokens accepted by target verification",
)
spec_acceptance_rate = _get_or_create(
    Gauge,
    f"{_PREFIX}_spec_acceptance_rate",
    "Lifetime draft-token acceptance rate of speculative verify spans",
    labelnames=("replica",),
)

# ---- engine-state gauges (k8s autoscaling keys off exactly these; the
# reference exports the vLLM equivalents vllm:num_requests_running/
# waiting/gpu_cache_usage_perc through its /metrics).  Fed by the async
# engine's stats loop (engine/async_llm.py), aggregated over dp replicas.
num_requests_waiting = _get_or_create(
    Gauge,
    f"{_PREFIX}_num_requests_waiting",
    "Requests queued, not yet running",
)
kv_pages_total = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_pages_total",
    "KV cache pages in the pool (all replicas)",
)
kv_pages_used = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_pages_used",
    "KV cache pages currently allocated",
)
kv_cache_usage = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_cache_usage",
    "Fraction of KV cache pages in use (0-1)",
)
prefix_cache_hit_tokens = _get_or_create(
    Gauge,
    f"{_PREFIX}_prefix_cache_hit_tokens",
    "Cumulative prompt tokens served from the prefix cache",
)


# ---- --swap-space host KV swap (engine/core.py): preemption victims'
# pages copied to host and restored on re-admission instead of
# recompute-prefill.  Per dp replica (PR 7 gave the other engine
# counters the label; these two were left scribbling one shared series).
kv_swap_out_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_swap_out_total",
    "Preempted sequences whose KV pages were swapped to host memory, "
    "per dp replica",
    labelnames=("replica",),
)
kv_swap_in_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_swap_in_total",
    "Sequences restored from host KV swap instead of recompute-prefill, "
    "per dp replica",
    labelnames=("replica",),
)
kv_swap_used_bytes = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_swap_used_bytes",
    "Host bytes currently held by swapped-out KV copies",
)


# ---- tiered KV store (--kv-host-cache-gb, engine/kv_tier.py): the
# host-RAM hash-addressed prefix cache behind the device pool
# (docs/KV_TIERING.md).  Hit rate is tokens served from each tier over
# prompt tokens that consulted the prefix cache, cumulative per replica.
kv_prefix_hit_rate = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_prefix_hit_rate",
    "Fraction of prefix-cache-consulting prompt tokens served from each "
    "tier (tier=device: pages adopted from the device prefix cache; "
    "tier=host: pages promoted from the host-RAM KV tier), cumulative "
    "per dp replica",
    labelnames=("tier", "replica"),
)
kv_prefix_tokens_reused_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_prefix_tokens_reused_total",
    "Prompt tokens whose KV was reused instead of recomputed, by the "
    "tier that served them (device = prefix-cache adoption, host = "
    "host-tier promotion)",
    labelnames=("tier",),
)
kv_host_tier_bytes = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_host_tier_bytes",
    "Bytes held by each rung of the tiered KV store, by tier "
    "(tier=host: the --kv-host-cache-gb hash-addressed RAM store; "
    "tier=disk: the --kv-disk-cache-gb spill files beneath it) — "
    "shared across dp replicas, never silently summed",
    labelnames=("tier",),
)
kv_host_tier_evictions_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kv_host_tier_evictions_total",
    "Entries evicted from each KV-store rung's byte-budgeted LRU "
    "(tier=host: RAM victims, which cascade to disk when the disk "
    "tier is on; tier=disk: unlinked files)",
    labelnames=("tier",),
)
arena_blocks = _get_or_create(
    Gauge,
    f"{_PREFIX}_arena_blocks",
    "Unified paged HBM arena occupancy by page type per dp replica "
    "(docs/MEMORY.md): type=adapter (true-rank pages charged by "
    "device-resident LoRA shards), type=kv_used (pages held by live "
    "or cached KV content), type=kv_free (allocatable)",
    labelnames=("type", "replica"),
)


# ---- quantized KV pages (--kv-quantization, ops/kv_quant.py,
# docs/QUANTIZATION.md): capacity is the whole point — the dtype label
# makes the ~2x page-count lift visible next to the HBM budget — and
# the logprob delta is the token-quality bound the scenario suites
# gate (tools/scenarios.py writes the last measured value here).
kv_page_capacity_blocks = _get_or_create(
    Gauge,
    f"{_PREFIX}_kv_page_capacity_blocks",
    "KV pages the device pool holds, labeled by the page storage dtype "
    "(bf16/f32 full precision, int8/fp8 quantized) per dp replica — "
    "the capacity the HBM budget buys under --kv-quantization",
    labelnames=("dtype", "replica"),
)
quant_logprob_delta = _get_or_create(
    Gauge,
    f"{_PREFIX}_quant_logprob_delta",
    "Mean per-token |logprob delta| of the quantized KV path vs the "
    "bf16 baseline, as last measured by the steady-state scenario "
    "suites (tools/scenarios.py; 0 until a suite has run)",
)


# ---- guided-decoding constraint compilation (engine/constrained.py
# compile_fsm): first use of a constraint compiles a DFA + token table
# synchronously; repeats hit the LRU.  These expose the latency spike
# and the hit rate.
constraint_cache_hits = _get_or_create(
    Counter,
    f"{_PREFIX}_constraint_cache_hits",
    "Guided-decoding constraints served from the compiled-FSM cache",
)
constraint_cache_misses = _get_or_create(
    Counter,
    f"{_PREFIX}_constraint_cache_misses",
    "Guided-decoding constraints that required a fresh FSM compilation",
)
constraint_compile_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_constraint_compile_seconds",
    "Wall time of guided-decoding FSM compilation (DFA + token table)",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)


# ---- MoE capacity-dispatch observability (judge r4 weak #5): capacity
# routing drops over-capacity assignments SILENTLY inside the jitted
# forward; these make the accuracy/throughput trade visible.  Fed from
# the model via io_callback on single-device engines (models/llama.py
# _moe_capacity_mlp; gated off under SPMD meshes where host callbacks
# would serialize the collective schedule).
moe_dropped_assignments_total = _get_or_create(
    Counter,
    f"{_PREFIX}_moe_dropped_assignments_total",
    "MoE (token, expert) assignments dropped for exceeding expert "
    "capacity under --moe-dispatch capacity",
)
moe_assignments_total = _get_or_create(
    Counter,
    f"{_PREFIX}_moe_assignments_total",
    "Total MoE (token, expert) assignments routed under capacity dispatch",
)
moe_expert_capacity = _get_or_create(
    Gauge,
    f"{_PREFIX}_moe_expert_capacity",
    "Realized per-expert buffer rows of the most recent MoE dispatch "
    "(ceil(T*k/E * capacity_factor), bounded by T)",
)


# ---- step-level engine telemetry (docs/OBSERVABILITY.md): per-token
# latency, per-dispatch batch-shape efficiency, preemption pressure, and
# XLA compilation discipline.  Fed from the engine core's plan/commit
# phases and the runner's jit wrappers (compile_tracker.py); collection
# is never gated by --disable-log-stats (that flag only silences the
# periodic log LINE, engine/async_llm.py).
ttft_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_ttft_seconds",
    "Time to first token: request arrival to the first sampled token "
    "committing on host (the live counterpart of the bench's ttft_ms)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0),
)
inter_token_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_inter_token_seconds",
    "Inter-token latency; fused multi-step waves commit K tokens at "
    "once, so each of the wave's tokens observes the wave gap / K",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5),
)
decode_step_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_decode_step_seconds",
    "Wall time of one fused decode dispatch, plan to commit, per dp "
    "replica and replica role (prefill/decode/mixed)",
    labelnames=("replica", "replica_role"),
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0),
)
prefill_step_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_prefill_step_seconds",
    "Wall time of one prefill (chunk or packed) dispatch, plan to "
    "commit, per dp replica and replica role (prefill/decode/mixed)",
    labelnames=("replica", "replica_role"),
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0),
)
decode_batch_occupancy = _get_or_create(
    Gauge,
    f"{_PREFIX}_decode_batch_occupancy",
    "Real sequences / padded batch bucket of the most recent decode "
    "dispatch (0-1), per dp replica; low values mean the compile "
    "bucket is mostly pad",
    labelnames=("replica",),
)
prefill_padding_waste = _get_or_create(
    Gauge,
    f"{_PREFIX}_prefill_padding_waste",
    "Padded fraction of the most recent prefill dispatch's token bucket "
    "(0-1)",
)
padded_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_padded_tokens_total",
    "Cumulative token slots dispatched as padding, by phase — the "
    "device work bucketed shapes waste to stay compile-bounded",
    labelnames=("phase",),
)
packed_prefill_prompts = _get_or_create(
    Histogram,
    f"{_PREFIX}_packed_prefill_prompts",
    "Whole prompts packed into one prefill dispatch (1 = solo prefill)",
    buckets=(1, 2, 3, 4, 5, 6, 7, 8),
)
preemptions_total = _get_or_create(
    Counter,
    f"{_PREFIX}_preemptions_total",
    "Sequences preempted because the KV page pool ran dry",
)
xla_recompile_total = _get_or_create(
    Counter,
    f"{_PREFIX}_xla_recompile_total",
    "XLA compile-cache misses per jitted entry point and dispatch "
    "shape (compile_tracker.py); steady-state serving should add none",
    labelnames=("fn", "shape"),
)
xla_compile_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_xla_compile_seconds",
    "Wall time of dispatches that triggered an XLA compile (includes "
    "the traced execution itself)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
             120.0, 300.0),
)
xla_compiled_shapes = _get_or_create(
    Gauge,
    f"{_PREFIX}_xla_compiled_shapes",
    "Distinct (fn, shape) programs compiled since boot",
)
xla_compiled_shapes_by_backend = _get_or_create(
    Gauge,
    f"{_PREFIX}_xla_compiled_shapes_by_backend",
    "Distinct compiled (fn, shape) programs since boot, split by "
    "attention data path (backend=ragged counts the ragged_* entry "
    "points, backend=bucketed everything else) — the direct evidence "
    "for the ragged path's collapsed compile lattice",
    labelnames=("backend",),
)
ragged_batch_fill_ratio = _get_or_create(
    Gauge,
    f"{_PREFIX}_ragged_batch_fill_ratio",
    "Real tokens / flat-length bucket of the most recent ragged "
    "dispatch (0-1); ~1 whenever prefill backlog exists — the ragged "
    "path's replacement for per-prompt bucket padding "
    "(--attention-backend=ragged)",
)


# ---- flight recorder + stall watchdog (flight_recorder.py /
# watchdog.py): the black-box half of observability.  The events counter
# makes recorder throughput alertable (a silent recorder during an
# incident is itself a finding); the heartbeat-age gauge and stall
# counter turn step-loop hangs into pageable signals instead of
# dump-files nobody reads until the postmortem.
flight_recorder_events_total = _get_or_create(
    Counter,
    f"{_PREFIX}_flight_recorder_events_total",
    "Request lifecycle events recorded in the flight-recorder ring, by "
    "event kind (admit/prefill/decode/preempt/swap/finish/abort/...)",
    labelnames=("kind",),
)
watchdog_last_heartbeat_age_seconds = _get_or_create(
    Gauge,
    f"{_PREFIX}_watchdog_last_heartbeat_age_seconds",
    "Seconds since the engine step loop last beat the stall watchdog "
    "(sampled on every watchdog tick)",
)
watchdog_stalls_total = _get_or_create(
    Counter,
    f"{_PREFIX}_watchdog_stalls_total",
    "Step-loop stalls the watchdog detected (heartbeat older than the "
    "deadline with work in flight and no compile in progress)",
)


# ---- engine supervision (supervisor/): supervised restart after engine
# death, with pre-prefill request replay (docs/RECOVERY.md)
engine_restarts_total = _get_or_create(
    Counter,
    f"{_PREFIX}_engine_restarts_total",
    "Supervised engine restarts, by death cause (step_loop, oom, stall, "
    "recovery_failure) and dp replica index",
    labelnames=("cause", "replica"),
)
requests_replayed_total = _get_or_create(
    Counter,
    f"{_PREFIX}_requests_replayed_total",
    "Requests transparently re-queued into a rebuilt engine after a "
    "supervised restart (pre-prefill work only: zero tokens had been "
    "emitted, so replay cannot duplicate output)",
)
recovery_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_recovery_seconds",
    "Wall time of one supervised engine recovery: quiesce, triage, "
    "rebuild (incl. precompile re-warm), replay, re-arm",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
)
requests_resumed_total = _get_or_create(
    Counter,
    f"{_PREFIX}_requests_resumed_total",
    "Mid-decode requests resumed from a decode checkpoint after engine "
    "death (docs/RECOVERY.md): 'local' = into the rebuilt replica, "
    "'cross_replica' = onto a healthy dp sibling before the rebuild",
    labelnames=("path",),
)
decode_checkpoints_total = _get_or_create(
    Counter,
    f"{_PREFIX}_decode_checkpoints_total",
    "Quiesce-time outcomes for mid-decode requests, by outcome: "
    "'resumed' = checkpointed into the host KV tier and resumed "
    "token-identically; 'fallback' = the degradation ladder kept the "
    "pre-resume semantics (tier disabled, --no-decode-resume, "
    "checkpoint over the tier budget, or a failed validation read) and "
    "the request failed retryable (EngineRestartError)",
    labelnames=("outcome",),
)
checkpoint_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_checkpoint_seconds",
    "Wall time to checkpoint one mid-decode request at quiesce: "
    "frontier-capped KV page gathers, host-tier commit, and the "
    "validation read",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)


# ---- front door (frontdoor/): admission control, per-tenant fair
# queuing, load shedding (docs/FRONTDOOR.md).  Queue depth/age cover
# the fair queue in FRONT of the engines (the scheduler's own waiting
# queues feed num_requests_waiting, which also includes these); sheds
# are the requests deliberately refused under overload, by reason.
frontdoor_queue_depth = _get_or_create(
    Gauge,
    f"{_PREFIX}_frontdoor_queue_depth",
    "Requests parked in the front-door fair queue, not yet handed to "
    "an engine scheduler",
)
frontdoor_queue_age_seconds = _get_or_create(
    Gauge,
    f"{_PREFIX}_frontdoor_queue_age_seconds",
    "Age of the oldest request parked in the front-door fair queue "
    "(0 when empty)",
)
frontdoor_sheds_total = _get_or_create(
    Counter,
    f"{_PREFIX}_frontdoor_sheds_total",
    "Requests shed by admission control, by reason (queue_full, "
    "deadline, rate_limit, ttl, draining)",
    labelnames=("reason",),
)
frontdoor_tenant_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_frontdoor_tenant_tokens_total",
    "Token budget (prompt + max new) accepted into the front door per "
    "tenant — the fair-queue cost unit (tenant label capped at 64 "
    "distinct values, then 'other')",
    labelnames=("tenant",),
)
frontdoor_placement_total = _get_or_create(
    Counter,
    f"{_PREFIX}_frontdoor_placement_total",
    "Requests placed onto a dp replica by the placement router, by the "
    "policy that won: prefix (prompt prefix resident in that replica's "
    "cache), tenant (tenant/adapter stickiness), load (least-loaded "
    "fallback); replica_role is the CHOSEN replica's disaggregation "
    "role (docs/SCALING.md).  Never incremented at --dp-replicas 1 "
    "(single-replica routing short-circuits)",
    labelnames=("policy", "replica_role"),
)

# ------------------------------- prefill/decode disaggregation (handoff)

handoffs_total = _get_or_create(
    Counter,
    f"{_PREFIX}_handoffs_total",
    "Prefill→decode handoffs (docs/SCALING.md 'Disaggregated roles'), "
    "by outcome: 'completed' = the staged checkpoint resumed on a "
    "decode-capable replica; 'fallback' = the degradation ladder "
    "exhausted (capture failure, validation-read failure, no decode "
    "replica serving, resume failure) and the request failed retryable "
    "(HandoffError → UNAVAILABLE/503 + Retry-After)",
    labelnames=("outcome",),
)
handoff_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_handoff_seconds",
    "Wall time of one completed prefill→decode handoff: capture at "
    "prefill commit (frontier-capped page gathers + checkpoint "
    "staging) through validation read, placement, and resume on the "
    "decode replica",
    buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
)

# ---------------------------------------- networked KV tier (kvnet/,
# docs/CROSS_HOST.md): cross-host prefix sharing + remote handoffs.
# obs_check hard-gates every name here.

kvnet_remote_lookups_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kvnet_remote_lookups_total",
    "KV page digests asked of kvnet peers during promotion assembly "
    "(the remote rung's fetch fan-out, before hit/miss is known)",
)
kvnet_remote_hits_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kvnet_remote_hits_total",
    "KV pages served BY a kvnet peer into a local promotion "
    "(checksum-validated entry blobs; each one is prefill compute "
    "this host did not repeat)",
)
kvnet_remote_hit_ratio = _get_or_create(
    Gauge,
    f"{_PREFIX}_kvnet_remote_hit_ratio",
    "Lifetime fraction of remote page lookups a peer actually served "
    "(hits/lookups; 0 until the first remote promotion)",
)
kvnet_transfer_bytes_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kvnet_transfer_bytes_total",
    "Bytes of kvnet page/checkpoint payload moved over the wire, by "
    "direction ('in' = received from peers, 'out' = sent to peers)",
    labelnames=("direction",),
)
kvnet_peer_rtt_seconds = _get_or_create(
    Gauge,
    f"{_PREFIX}_kvnet_peer_rtt_seconds",
    "EWMA round-trip time of kvnet requests, per peer address "
    "(heartbeat PINGs keep it fresh while idle)",
    labelnames=("peer",),
)
kvnet_peers = _get_or_create(
    Gauge,
    f"{_PREFIX}_kvnet_peers",
    "Configured kvnet peers by degradation state: 'healthy' (serving), "
    "'degraded' (recent failures, still answering), 'down' "
    "(disconnected; coverage and handoffs skip it until the heartbeat "
    "revives it)",
    labelnames=("state",),
)
kvnet_handoffs_total = _get_or_create(
    Counter,
    f"{_PREFIX}_kvnet_handoffs_total",
    "Cross-host DecodeCheckpoint handoffs by outcome: source side "
    "'remote' (peer accepted decode) / 'stage_failed' / 'commit_lost' "
    "/ 'rejected' / 'peer_lost'; target side 'staged' / 'accepted' / "
    "'adopted' (machine-loss resume of a dead source's staged record) "
    "/ 'validation' / 'no_replica' / 'resume'",
    labelnames=("outcome",),
)

# ------------------------------------------------------ LoRA adapter pool

lora_adapters_registered = _get_or_create(
    Gauge,
    f"{_PREFIX}_lora_adapters_registered",
    "LoRA adapters registered in the host-RAM registry "
    "(engine/lora.py LoRAManager; bounded by --max-cpu-loras in pool "
    "mode, --max-loras on the legacy path)",
)
lora_adapters_resident = _get_or_create(
    Gauge,
    f"{_PREFIX}_lora_adapters_resident",
    "LoRA adapters currently device-resident in the replica's paged "
    "adapter pool (engine/adapter_pool.py; bounded by --max-loras)",
    labelnames=("replica",),
)
lora_swap_total = _get_or_create(
    Counter,
    f"{_PREFIX}_lora_swap_total",
    "Adapter pool slot swaps, by direction: 'in' = host→device stream "
    "committed, 'out' = LRU eviction / host-registry invalidation "
    "freed a slot",
    labelnames=("direction",),
)
lora_pool_hit_rate = _get_or_create(
    Gauge,
    f"{_PREFIX}_lora_pool_hit_rate",
    "Fraction of adapter-bearing admissions whose adapter was already "
    "device-resident in the replica's pool (counted once per request "
    "at admission, not per schedule retry)",
    labelnames=("replica",),
)
lora_prefetch_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_lora_prefetch_seconds",
    "Host→device adapter stream latency (block build + transfer + "
    "jitted slot scatter), per committed stream",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)


# ---- telemetry signal layer (ISSUE 16, telemetry/): per-tenant cost
# attribution from the request ledger, per-class SLO attainment/burn,
# and the live efficiency gauges the elastic control plane (ROADMAP
# item 4) keys its placement/capacity decisions off.

tenant_cost_tokens_total = _get_or_create(
    Counter,
    f"{_PREFIX}_tenant_cost_tokens_total",
    "Tokens (prompt + generated) billed to each tenant and request "
    "class by the cost ledger at terminal outcome "
    "(telemetry/ledger.py; tenant labels bounded, overflow → 'other')",
    labelnames=("tenant", "class"),
)
tenant_cost_hbm_page_seconds_total = _get_or_create(
    Counter,
    f"{_PREFIX}_tenant_cost_hbm_page_seconds_total",
    "KV page-seconds of device HBM held per tenant and request class "
    "(pages owned x wall seconds, sampled at each commit boundary) — "
    "the memory-occupancy half of cost attribution",
    labelnames=("tenant", "class"),
)
tenant_cost_tier_bytes_total = _get_or_create(
    Counter,
    f"{_PREFIX}_tenant_cost_tier_bytes_total",
    "Host KV-tier bytes moved (demotions + promotions) on behalf of "
    "each tenant and request class",
    labelnames=("tenant", "class"),
)
slo_attainment = _get_or_create(
    Gauge,
    f"{_PREFIX}_slo_attainment",
    "Fraction of recent (5m window) observations inside each declared "
    "objective, per request class (telemetry/slo.py; objective = "
    "ttft | itl | availability; 1.0 with no traffic)",
    labelnames=("class", "objective"),
)
slo_burn_rate = _get_or_create(
    Gauge,
    f"{_PREFIX}_slo_burn_rate",
    "Worst per-objective error-budget burn rate per request class and "
    "sliding window (5m/1h): bad_fraction / error_budget — 1.0 burns "
    "the budget exactly at the exhaustion rate, >1.0 is the paging "
    "threshold",
    labelnames=("class", "window"),
)
spec_acceptance_rate_ewma = _get_or_create(
    Gauge,
    f"{_PREFIX}_spec_acceptance_rate_ewma",
    "Time-decayed (30s half-life) EWMA of the per-dispatch speculative "
    "acceptance rate, per dp replica — the responsive signal the "
    "gamma auto-tuner consumes (lifetime rate: spec_acceptance_rate)",
    labelnames=("replica",),
)
model_tflops_per_s = _get_or_create(
    Gauge,
    f"{_PREFIX}_model_tflops_per_s",
    "Achieved model TFLOP/s per dp replica from the live committed-"
    "token rate (telemetry/mfu.py: ~2 FLOPs/weight/token, the "
    "standard MFU numerator)",
    labelnames=("replica",),
)
mfu = _get_or_create(
    Gauge,
    f"{_PREFIX}_mfu",
    "Model FLOPs utilization per dp replica: achieved model FLOP/s "
    "over the TGIS_PEAK_TFLOPS-declared per-chip peak; exported only "
    "when the operator sets the peak (the CPU proxy has none)",
    labelnames=("replica",),
)
step_anatomy_seconds = _get_or_create(
    Histogram,
    f"{_PREFIX}_step_anatomy_seconds",
    "Per-step phase decomposition (telemetry/steptime.py): plan / "
    "prepare / dispatch / device_wait / commit / host_gap, per dp "
    "replica — the six phases sum to the step wall exactly",
    labelnames=("phase", "replica"),
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
host_gap_frac = _get_or_create(
    Gauge,
    f"{_PREFIX}_host_gap_frac",
    "Sliding-window fraction of step wall the device sat idle waiting "
    "on the host (telemetry/steptime.py) per dp replica — ~0 when the "
    "pipelined loop overlaps host prep with device execution; the "
    "doctor's host_bound input",
    labelnames=("replica",),
)
doctor_episodes_total = _get_or_create(
    Counter,
    f"{_PREFIX}_doctor_episodes_total",
    "Bottleneck-doctor episodes opened, per regime (host_bound / "
    "compile_storm / queue_bound / tier_thrash / "
    "allocator_fragmentation / spec_unprofitable) and dp replica "
    "(telemetry/doctor.py)",
    labelnames=("regime", "replica"),
)
doctor_active_regimes = _get_or_create(
    Gauge,
    f"{_PREFIX}_doctor_active_regimes",
    "Currently open bottleneck-doctor episodes across the fleet — "
    "nonzero means the doctor is attributing degraded serving to a "
    "named regime right now (/debug/doctor has the evidence)",
)


class _StepSnapshot:
    """Host-side mirror of the latest per-dispatch shape stats, so the
    periodic stats log line (engine/async_llm.py) can report them without
    reading gauge internals back out of prometheus_client."""

    __slots__ = ("decode_occupancy", "prefill_padding_waste",
                 "decode_steps", "prefill_steps")

    def __init__(self) -> None:
        self.decode_occupancy = 0.0
        self.prefill_padding_waste = 0.0
        self.decode_steps = 0
        self.prefill_steps = 0


step_snapshot = _StepSnapshot()


def observe_decode_plan(*, num_seqs: int, batch_bucket: int,
                        num_steps: int, replica: int = 0) -> None:
    occupancy = num_seqs / batch_bucket if batch_bucket else 0.0
    decode_batch_occupancy.labels(replica=str(replica)).set(occupancy)
    padded = (batch_bucket - num_seqs) * num_steps
    if padded > 0:
        padded_tokens_total.labels(phase="decode").inc(padded)
    step_snapshot.decode_occupancy = occupancy
    step_snapshot.decode_steps += 1


def observe_prefill_plan(*, real_tokens: int, bucket: int,
                         num_prompts: int) -> None:
    waste = (bucket - real_tokens) / bucket if bucket else 0.0
    prefill_padding_waste.set(waste)
    if bucket > real_tokens:
        padded_tokens_total.labels(phase="prefill").inc(bucket - real_tokens)
    packed_prefill_prompts.observe(num_prompts)
    step_snapshot.prefill_padding_waste = waste
    step_snapshot.prefill_steps += 1


def observe_ragged_plan(*, real_tokens: int, bucket: int,
                        num_prefill: int, num_decode: int) -> None:
    """Shape stats for one unified ragged dispatch
    (--attention-backend=ragged).  The padding-waste gauge reads from
    the RAGGED plan here — the bucketed gauges must not report stale
    bucket math when the ragged path is serving."""
    fill = real_tokens / bucket if bucket else 0.0
    ragged_batch_fill_ratio.set(fill)
    prefill_padding_waste.set(1.0 - fill)
    if bucket > real_tokens:
        padded_tokens_total.labels(phase="ragged").inc(bucket - real_tokens)
    if num_prefill:
        packed_prefill_prompts.observe(num_prefill)
    step_snapshot.prefill_padding_waste = 1.0 - fill
    step_snapshot.prefill_steps += 1


def record_moe_dispatch(dropped: int, total: int, capacity: int) -> None:
    moe_dropped_assignments_total.inc(int(dropped))
    moe_assignments_total.inc(int(total))
    moe_expert_capacity.set(int(capacity))


def update_engine_gauges(
    *,
    waiting: int,
    kv_used: int,
    kv_total: int,
    prefix_hits: int,
) -> None:
    # num_requests_running is NOT set here: the serving layer inc/decs it
    # per request (tgis_utils/logs.py) and a periodic .set() from a
    # second writer would flip-flop the two views
    num_requests_waiting.set(waiting)
    kv_pages_used.set(kv_used)
    kv_pages_total.set(kv_total)
    kv_cache_usage.set(kv_used / kv_total if kv_total else 0.0)
    prefix_cache_hit_tokens.set(prefix_hits)


def record_response(
    *,
    kind: str,
    prompt_tokens: int,
    generated_tokens: int,
    duration_s: float,
    queue_s: float,
) -> None:
    request_count.labels(kind=kind).inc()
    prompt_tokens_total.inc(prompt_tokens)
    generated_tokens_total.inc(generated_tokens)
    request_duration.observe(duration_s)
    queue_duration.observe(queue_s)


def render() -> bytes:
    return generate_latest(REGISTRY)
