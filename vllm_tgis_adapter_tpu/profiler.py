"""On-demand device profiling backed by ``jax.profiler``.

Reference analog: vLLM's ``/start_profile`` / ``/stop_profile`` routes
(active when the torch profiler dir env var is set).  Here the capture is
a ``jax.profiler`` trace written under ``--profile-dir`` and viewable in
TensorBoard/XProf; both serving front-ends drive the SAME controller so a
capture started over HTTP can be stopped over gRPC and vice versa.

The controller is deliberately forgiving: profiling is operator tooling,
so a backend without a usable profiler (bare CPU CI images, stub
runtimes) degrades to a recorded no-op instead of failing the request or
— worse — the serving process.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from vllm_tgis_adapter_tpu.logging import init_logger

logger = init_logger(__name__)


class ProfilerError(ValueError):
    """Operator-facing misuse (disabled / double start / idle stop)."""


class ProfilerController:
    """Serializes jax.profiler trace capture behind a process-wide lock."""

    def __init__(self, profile_dir: Optional[str]):
        self.profile_dir = profile_dir
        self._lock = threading.Lock()
        self._active = False
        self._noop = False
        self._started_at: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    @property
    def active(self) -> bool:
        return self._active

    def start(self) -> dict:
        if not self.enabled:
            raise ProfilerError(
                "profiling is disabled; restart the server with "
                "--profile-dir"
            )
        with self._lock:
            if self._active:
                raise ProfilerError("a profiler capture is already active")
            self._noop = False
            try:
                import jax

                jax.profiler.start_trace(self.profile_dir)
            except Exception as e:  # noqa: BLE001 — profiling must not kill serving
                logger.warning(
                    "jax.profiler unavailable (%s); capture is a no-op", e
                )
                self._noop = True
            self._active = True
            self._started_at = time.time()
            logger.info("profiler capture started → %s", self.profile_dir)
            return {
                "status": "noop" if self._noop else "started",
                "profile_dir": self.profile_dir,
            }

    def stop(self) -> dict:
        if not self.enabled:
            raise ProfilerError(
                "profiling is disabled; restart the server with "
                "--profile-dir"
            )
        with self._lock:
            if not self._active:
                raise ProfilerError("no profiler capture is active")
            noop = self._noop
            if not noop:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    logger.warning("jax.profiler stop failed: %s", e)
                    noop = True
            duration = time.time() - (self._started_at or time.time())
            self._active = False
            self._started_at = None
            logger.info(
                "profiler capture stopped after %.2fs → %s",
                duration, self.profile_dir,
            )
            return {
                "status": "noop" if noop else "stopped",
                "profile_dir": self.profile_dir,
                "duration_seconds": duration,
            }


_controller: Optional[ProfilerController] = None
_controller_lock = threading.Lock()


def get_controller(profile_dir: Optional[str]) -> ProfilerController:
    """Process-wide controller: jax.profiler allows one trace at a time,
    so the HTTP and gRPC front-ends must share state."""
    global _controller
    with _controller_lock:
        if _controller is None:
            _controller = ProfilerController(profile_dir)
        elif profile_dir and not _controller.profile_dir:
            _controller.profile_dir = profile_dir
        return _controller


def reset_controller() -> None:
    """Test hook."""
    global _controller
    with _controller_lock:
        _controller = None
