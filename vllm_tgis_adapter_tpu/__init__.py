"""TPU-native text-generation serving framework.

Serves the TGIS-compatible ``fmaas.GenerationService`` gRPC API and an
OpenAI-compatible HTTP API from a single shared JAX/XLA inference engine,
mirroring the capability surface of ``vllm-tgis-adapter`` (reference:
/root/reference/src/vllm_tgis_adapter) with the engine itself implemented
TPU-natively instead of delegating to vLLM/CUDA.
"""

__version__ = "0.1.0"
version_tuple = (0, 1, 0)
