"""Process entrypoint: boot the engine, run gRPC + HTTP servers together.

Same lifecycle contract as the reference (__main__.py:38-131): bind the
HTTP socket before engine boot, build ONE shared engine, wrap it with the
TGIS logging hooks, launch both servers as tasks, cancel the survivor when
either exits, re-raise the first failure, and record the cause of death in
the Kubernetes termination log.
"""

from __future__ import annotations

import asyncio
import os
import socket
import traceback
from typing import TYPE_CHECKING

from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
from vllm_tgis_adapter_tpu.http import build_http_server, run_http_server
from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.tgis_utils import logs
from vllm_tgis_adapter_tpu.tgis_utils.args import (
    make_parser,
    postprocess_tgis_args,
)
from vllm_tgis_adapter_tpu.utils import (
    check_for_failed_tasks,
    spawn_task,
    write_termination_log,
)

if TYPE_CHECKING:
    import argparse

logger = init_logger(__name__)


class TaskFailedError(RuntimeError):
    pass


def create_server_socket(host: str | None, port: int) -> socket.socket:
    """Bind the HTTP port before the (slow) engine boot so probes can't
    race a half-started process (reference workaround, __main__.py:41-45)."""
    family = socket.AF_INET6 if host and ":" in host else socket.AF_INET
    sock = socket.socket(family=family, type=socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host or "", port))
    return sock


async def start_servers(args: "argparse.Namespace") -> None:
    level = getattr(args, "uvicorn_log_level", None)
    if level and level != "info":
        # flag name kept for reference compat; here it drives the HTTP
        # server module's own logger ("trace" maps below DEBUG)
        import logging as _logging

        _logging.getLogger("vllm_tgis_adapter_tpu.http").setLevel(
            5 if level == "trace" else level.upper()
        )
    sock = create_server_socket(args.host, args.port)

    if getattr(args, "jax_profiler_port", None):
        # device-level profiling story (SURVEY §5): TensorBoard/XProf
        # connects here to capture XLA/TPU traces of the live engine
        import jax

        jax.profiler.start_server(args.jax_profiler_port)
        logger.info(
            "jax.profiler server listening on port %d", args.jax_profiler_port
        )

    if getattr(args, "failpoints", None):
        # deliberate chaos-testing fault injection
        # (supervisor/failpoints.py; also via TGIS_TPU_FAILPOINTS) —
        # armed BEFORE engine boot so boot-path sites can fire too
        from vllm_tgis_adapter_tpu.supervisor import failpoints

        failpoints.arm(args.failpoints)

    engine = None
    drain = None
    tasks: list[asyncio.Task] = []
    drain_waiter: asyncio.Task | None = None
    dead_waiter: asyncio.Task | None = None
    loop = asyncio.get_running_loop()
    try:
        from vllm_tgis_adapter_tpu.engine.config import EngineConfig

        engine = AsyncLLMEngine.from_config(EngineConfig.from_args(args))
        if getattr(args, "enable_lora", False) and getattr(
            args, "lora_modules", None
        ):
            # static boot registration (name=path ...): adapters are
            # host-registered up front; device residency streams on
            # demand through the paged pool (docs/LORA.md)
            manager = engine.engine.lora_manager
            for spec in args.lora_modules:
                name, _, path = spec.partition("=")
                if not name or not path:
                    raise ValueError(
                        f"--lora-modules entry {spec!r} is not name=path"
                    )
                await manager.load_lora_adapter(name, path)
        if getattr(args, "precompile", None):
            # warm every serving shape BEFORE the servers bind: the
            # first real request then never pays a 20-40s TPU compile
            await engine.precompile(args.precompile)
        await engine.start()

        # uniform TGIS-style request logging for both servers
        logs.add_logging_wrappers(engine)

        # graceful drain (frontdoor/drain.py): SIGTERM stops admission
        # (health → DRAINING/503), in-flight generations finish up to
        # --drain-grace, the termination log is checkpointed, and only
        # then are the server tasks torn down
        from vllm_tgis_adapter_tpu.frontdoor.drain import DrainCoordinator

        drain = DrainCoordinator(
            engine,
            grace_s=engine.engine.config.frontdoor.drain_grace_s,
        )
        drain.install(loop)

        # imported at point of use, not module top: the pb2 modules
        # behind the gRPC server are protoc-generated, and a boot
        # failure BEFORE the servers (bad model path, config
        # validation) must still reach the termination log on hosts
        # without protoc — tests/test_termination_log.py exercises
        # exactly that
        from vllm_tgis_adapter_tpu.grpc.grpc_server import (
            run_grpc_server,
        )

        http_app = build_http_server(args, engine)

        tasks = [
            spawn_task(
                run_http_server(args, engine, http_app, sock),
                name="http_server", loop=loop,
            ),
            spawn_task(
                run_grpc_server(args, engine),
                name="grpc_server", loop=loop,
            ),
        ]

        with_task_names = ", ".join(t.get_name() for t in tasks)
        logger.info("Started tasks: %s", with_task_names)

        drain_waiter = spawn_task(
            drain.shutdown_event.wait(), name="drain_shutdown", loop=loop,
        )
        # terminal engine death (unsupervised, or the supervisor's
        # crash-loop circuit breaker) wakes this wait directly — the
        # process must exit promptly, not at the next RPC.  Supervised
        # restarts never set this: the engine recovers in place.
        dead_waiter = spawn_task(
            engine.dead_event.wait(), name="engine_dead", loop=loop,
        )
        done, _pending = await asyncio.wait(
            [*tasks, drain_waiter, dead_waiter],
            return_when=asyncio.FIRST_COMPLETED,
        )

        if drain_waiter in done:
            # drained to completion: this is the clean exit path — the
            # finally block cancels the (idle) servers
            logger.info("drain complete; shutting down servers")
            return

        if engine.errored:
            # surface the engine failure rather than a generic task error
            raise engine.dead_error

        for task in done:
            if (exception := task.exception()) is not None:
                raise TaskFailedError(
                    f"task {task.get_name()} failed"
                ) from exception
    finally:
        if drain is not None:
            drain.uninstall(loop)
        for waiter in (drain_waiter, dead_waiter):
            if waiter is not None and not waiter.done():
                waiter.cancel()
        for task in tasks:
            if not task.done():
                task.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        if engine is not None:
            await engine.stop()
        sock.close()

    failed = check_for_failed_tasks(tasks)
    if failed is not None:
        raise TaskFailedError(f"task {failed.get_name()} failed") from (
            failed.exception()
        )


def run_and_catch_termination_cause(
    loop: asyncio.AbstractEventLoop, task: asyncio.Task
) -> None:
    try:
        loop.run_until_complete(task)
    except BaseException:
        # report the first exception as the cause of termination;
        # APPENDED so an engine-death report / restart-history
        # checkpoint already written this process survives alongside it
        msg = traceback.format_exc()
        write_termination_log(
            msg, os.getenv("TERMINATION_LOG_DIR", "/dev/termination-log"),
            append=True,
        )
        raise


def main() -> None:
    parser = make_parser()
    args = postprocess_tgis_args(parser.parse_args())
    if not args.model:
        parser.error("--model (or --model-name / MODEL_NAME env) is required")

    try:
        # faster event loop for the per-token wire hot path (reference
        # installs it unconditionally, __main__.py:10,128); optional here
        # so the framework runs on images without the wheel
        import uvloop

        asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
        logger.info("using uvloop event loop")
    except ImportError:
        pass

    loop = asyncio.new_event_loop()
    try:
        task = spawn_task(start_servers(args), name="start_servers", loop=loop)
        run_and_catch_termination_cause(loop, task)
    finally:
        loop.close()


if __name__ == "__main__":
    main()
