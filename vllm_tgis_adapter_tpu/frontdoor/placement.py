"""Affinity-aware replica placement for data-parallel serving.

With ``--dp-replicas N`` (or ``--data-parallel-size N``) the front door
fronts N independent engine replicas, each with its own scheduler, KV
pool, and prefix cache.  WHERE a request lands then matters twice over:

* **prefix-cache affinity** — a replica whose paged cache already holds
  the request's prompt prefix serves prefill nearly for free
  (``BlockAllocator.peek_prefix``: a pure hash walk, no refcounts);
  routing the request anywhere else re-computes KV that exists on the
  fleet.  This is the cache-aware routing the data-parallel serving
  literature converges on (PAPERS.md: Orca-style continuous-batching
  replicas; the SGLang/Mooncake cache-aware router family).
* **tenant/adapter affinity** — a tenant's LoRA stack and its WFQ
  virtual-time state live wherever its requests land; sticky placement
  keeps an adapter resident on one replica instead of faulting it into
  every pool in rotation.
* **load** — both affinities yield to load: a replica more than
  ``load_slack`` requests deeper than the least-loaded one is not
  eligible for affinity placement, so a hot prefix or a chatty tenant
  cannot pile a replica over while its siblings idle.

``place()`` is a pure function of the snapshots handed to it — the
async engine builds one ``ReplicaSnapshot`` per SERVING replica (dead
and recovering replicas are excluded by the caller, so placement drains
away from a replica the moment its supervisor quiesces it) and routes
the request to the returned index.  Scoring order: role (prefill/
decode disaggregation, docs/SCALING.md "Disaggregated roles") >
prefix > adapter > tenant > least-loaded.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Optional

from vllm_tgis_adapter_tpu import metrics
from vllm_tgis_adapter_tpu.logging import init_logger

logger = init_logger(__name__)

POLICY_PREFIX = "prefix"
POLICY_ADAPTER = "adapter"
POLICY_TENANT = "tenant"
POLICY_LOAD = "load"
POLICIES = (POLICY_PREFIX, POLICY_ADAPTER, POLICY_TENANT, POLICY_LOAD)

# replica-role capability sets (docs/SCALING.md "Disaggregated roles"):
# the role TIER sits above every affinity policy — fresh requests run
# their prompt on prefill-capable replicas, handoff/checkpoint resumes
# decode on decode-capable ones.  A 'mixed' replica is both.
ROLE_CAPABLE = {
    "prefill": ("prefill", "mixed"),
    "decode": ("decode", "mixed"),
}

# EWMA weight for the per-replica committed-token rate (load tiebreak +
# bench attribution); one sample ~= one committed dispatch
_EWMA_ALPHA = 0.3

# host-tier residency scores below device residency (a promotion still
# pays a host→device transfer; an adopted device page is free): one
# host-resident token is worth this fraction of a device-resident one
HOST_TIER_WEIGHT = 0.25

# kvnet-peer residency scores below even the host tier (a remote hit
# pays a network fetch AND the host→device transfer; docs/CROSS_HOST.md
# "degradation ladder"): better than recompute, worse than any local rung
REMOTE_TIER_WEIGHT = 0.1


@dataclasses.dataclass
class ReplicaSnapshot:
    """One serving replica's placement-relevant state at decision time.

    ``load`` is the scheduler's queue depth (waiting + running);
    ``prefix_tokens`` is the length of THIS request's prompt prefix
    already resident in the replica's paged cache (0 when prefix
    caching is off or the caller skipped the probe).
    """

    index: int
    load: float
    prefix_tokens: int = 0
    # prompt tokens the HOST KV tier could promote for this request
    # (engine/kv_tier.py; the tier is fleet-shared, so the caller stamps
    # the same value on every snapshot) — scored at a lower weight than
    # device residency: a promotion still pays a host→device transfer
    host_prefix_tokens: int = 0
    # prompt tokens only a kvnet PEER could serve (fleet coverage minus
    # local coverage — engine/async_llm.py computes the split with two
    # peek_prefix_pages walks); scored below the host tier: a remote
    # hit pays a network fetch on top of the host→device transfer
    remote_prefix_tokens: int = 0
    # this request's LoRA adapter is live in the replica's device pool
    # (engine/adapter_pool.py) — TRUE residency, read at decision time,
    # unlike the sticky map which only remembers past placements
    adapter_resident: bool = False
    # the replica's disaggregation role (prefill/decode/mixed) — the
    # role TIER filters candidates before any affinity policy scores
    replica_role: str = "mixed"


class PlacementRouter:
    """Scores replicas for each request and remembers tenant stickiness.

    Host-side only, event-loop confined (no locks needed): ``place()``
    runs in ``generate()`` and ``note_committed()`` in the step loops'
    commit phase, both on the one event-loop thread.
    """

    def __init__(
        self,
        *,
        load_slack: float = 2.0,
        max_sticky_tenants: int = 1024,
    ):
        # affinity placement is only allowed onto replicas within this
        # many queued requests of the least-loaded one — the guard that
        # keeps a hot prefix or sticky tenant from overloading a replica
        self.load_slack = load_slack
        # tenant/adapter -> replica index of the last placement; bounded
        # LRU because tenant ids are client-controlled
        self._sticky: "OrderedDict[str, int]" = OrderedDict()
        self._max_sticky = max_sticky_tenants
        #: lifetime placements by policy (debug_state + bench stamps)
        self.placed_by_policy: dict[str, int] = {p: 0 for p in POLICIES}
        #: lifetime placements per replica index
        self.placed_by_replica: dict[int, int] = {}
        # per-replica committed-token accounting (commit-phase feed):
        # lifetime totals for bench attribution, EWMA rate for the load
        # tiebreak between equally-deep queues
        self._committed_total: dict[int, float] = {}
        self._committed_rate: dict[int, float] = {}

    # ------------------------------------------------------------- feeds

    def note_committed(self, replica: int, tokens: float) -> None:
        """One committed dispatch's token count on ``replica``."""
        self._committed_total[replica] = (
            self._committed_total.get(replica, 0.0) + tokens
        )
        prev = self._committed_rate.get(replica)
        self._committed_rate[replica] = (
            tokens
            if prev is None
            else (1 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * tokens
        )

    def forget_replica_rate(self, replica: int) -> None:
        """A replica was rebuilt: its in-flight rate is history."""
        self._committed_rate.pop(replica, None)

    def committed_by_replica(self) -> dict[int, float]:
        """Lifetime committed-token totals per replica (bench stamps)."""
        return dict(self._committed_total)

    # --------------------------------------------------------- placement

    def _sticky_get(self, key: str) -> Optional[int]:
        idx = self._sticky.get(key)
        if idx is not None:
            self._sticky.move_to_end(key)
        return idx

    def _sticky_set(self, key: str, idx: int) -> None:
        self._sticky[key] = idx
        self._sticky.move_to_end(key)
        while len(self._sticky) > self._max_sticky:
            self._sticky.popitem(last=False)

    def place(
        self,
        snapshots: list[ReplicaSnapshot],
        *,
        affinity_key: Optional[str] = None,
        kind: str = "prefill",
    ) -> tuple[int, str]:
        """Pick a replica for one request.

        ``snapshots`` must be non-empty and contain only replicas the
        caller is willing to use (serving ones; the caller's fallback
        for a fleet with zero serving replicas is its own).
        ``affinity_key`` is the tenant id or adapter name — ``None``
        (anonymous default-tenant traffic) gets no stickiness, so bulk
        un-tenanted load spreads purely by depth.

        ``kind`` drives the ROLE tier above every other policy
        (docs/SCALING.md "Disaggregated roles"): ``"prefill"`` (fresh
        requests and replays — they must run their prompt) restricts to
        prefill-capable replicas, ``"decode"`` (handoff/checkpoint
        resumes) to decode-capable ones.  If no capable replica is in
        the candidate set, the filter falls open to the full set —
        availability beats role purity during a partial outage (callers
        that must NOT degrade, like the handoff drain, pre-check
        capability and fail retryable instead).

        Returns ``(replica_index, policy)`` with policy one of
        ``prefix`` / ``adapter`` / ``tenant`` / ``load``.
        """
        capable_roles = ROLE_CAPABLE.get(kind, ROLE_CAPABLE["prefill"])
        capable = [
            s for s in snapshots if s.replica_role in capable_roles
        ]
        snapshots = capable or snapshots
        best_load = min(s.load for s in snapshots)
        eligible = [
            s for s in snapshots if s.load <= best_load + self.load_slack
        ]

        chosen: Optional[ReplicaSnapshot] = None
        policy = POLICY_LOAD

        # 1. prefix affinity: the most resident prompt tokens wins,
        # provided that replica is not already over the load slack.
        # Host-tier residency counts at HOST_TIER_WEIGHT below device
        # residency (docs/SCALING.md) — but only as an EXTENSION of a
        # device match: the tier is fleet-shared, so host-only coverage
        # carries no replica-discriminating information and must not
        # claim the prefix policy ahead of adapter/tenant affinity
        # (step 2c below is its weaker, post-affinity slot).
        def prefix_score(s: ReplicaSnapshot) -> float:
            host_extra = max(0, s.host_prefix_tokens - s.prefix_tokens)
            return (
                s.prefix_tokens
                + HOST_TIER_WEIGHT * host_extra
                + REMOTE_TIER_WEIGHT * s.remote_prefix_tokens
            )

        prefix_best = max(
            eligible, key=lambda s: (prefix_score(s), -s.load, -s.index)
        )
        if prefix_best.prefix_tokens > 0:
            chosen, policy = prefix_best, POLICY_PREFIX
        # 2a. true adapter-pool residency: a replica already holding the
        # adapter's device weights beats the sticky map's memory of past
        # placements (the adapter may have been evicted there since, or
        # streamed elsewhere by a replay)
        if chosen is None:
            resident = [s for s in eligible if s.adapter_resident]
            if resident:
                chosen = min(resident, key=lambda s: (s.load, s.index))
                policy = POLICY_ADAPTER
        # 2b. tenant/adapter stickiness
        if chosen is None and affinity_key is not None:
            sticky_idx = self._sticky_get(affinity_key)
            if sticky_idx is not None:
                for s in eligible:
                    if s.index == sticky_idx:
                        chosen, policy = s, POLICY_TENANT
                        break
        # 2c. host-only prefix coverage: every eligible replica can
        # promote the shared tier's pages equally, so take the least
        # loaded — still a prefix placement (the request skips the
        # prefill recompute), just subordinate to every affinity that
        # actually distinguishes replicas
        if chosen is None:
            hosted = [
                s for s in eligible
                if s.host_prefix_tokens > 0 or s.remote_prefix_tokens > 0
            ]
            if hosted:
                chosen = min(hosted, key=lambda s: (s.load, s.index))
                policy = POLICY_PREFIX
        # 3. least-loaded fallback; committed-rate EWMA breaks depth
        # ties toward the replica currently grinding fewer tokens
        if chosen is None:
            chosen = min(
                snapshots,
                key=lambda s: (
                    s.load,
                    self._committed_rate.get(s.index, 0.0),
                    s.index,
                ),
            )
            policy = POLICY_LOAD

        if affinity_key is not None:
            self._sticky_set(affinity_key, chosen.index)
        self.placed_by_policy[policy] += 1
        self.placed_by_replica[chosen.index] = (
            self.placed_by_replica.get(chosen.index, 0) + 1
        )
        try:
            metrics.frontdoor_placement_total.labels(
                policy=policy, replica_role=chosen.replica_role
            ).inc()
        except Exception:  # pragma: no cover — telemetry must not raise
            pass
        return chosen.index, policy

    # ------------------------------------------------------ introspection

    @property
    def placed_total(self) -> int:
        return sum(self.placed_by_policy.values())

    def affinity_hit_rate(self) -> float:
        """Fraction of placements won by an affinity policy (prefix or
        tenant) rather than the least-loaded fallback."""
        total = self.placed_total
        if total == 0:
            return 0.0
        hits = (
            self.placed_by_policy[POLICY_PREFIX]
            + self.placed_by_policy[POLICY_ADAPTER]
            + self.placed_by_policy[POLICY_TENANT]
        )
        return hits / total

    def debug_state(self) -> dict:
        """Router section of the engine's /debug/state snapshot."""
        return {
            "placed_by_policy": dict(self.placed_by_policy),
            "placed_by_replica": {
                str(k): v
                for k, v in sorted(self.placed_by_replica.items())
            },
            "affinity_hit_rate": round(self.affinity_hit_rate(), 4),
            "sticky_tenants": len(self._sticky),
            "committed_tokens_by_replica": {
                str(k): round(v, 1)
                for k, v in sorted(self._committed_total.items())
            },
        }
