"""Typed overload/exhaustion error taxonomy + wire-status mapping.

The serving layer used to classify engine failures by substring
(``"RESOURCE_EXHAUSTED" in str(exc)``, grpc_server pre-PR4) — brittle,
and it conflated three very different conditions: device HBM OOM (the
engine is probably dying), KV page-pool exhaustion (a sizing bug — the
pool cannot hold even one sequence), and deliberate front-door load
shedding (the server is healthy and the client should retry).  This
module is the single place where each condition gets a TYPE, and the
single table that maps those types onto gRPC status codes and HTTP
statuses, so the two API surfaces can never drift apart.

Text inspection of foreign exceptions still exists — it has to, XLA's
OOM surfaces as an ``XlaRuntimeError`` with a message — but it happens
in exactly one boundary function (``wrap_engine_error``), which converts
the foreign exception into a typed one the rest of the stack matches
with ``isinstance``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# shed reasons (AdmissionShedError.reason); docs/FRONTDOOR.md documents
# the wire semantics of each
SHED_QUEUE_FULL = "queue_full"    # --max-waiting-requests bound hit
SHED_DEADLINE = "deadline"        # est. queue drain > --admission-deadline
SHED_RATE_LIMIT = "rate_limit"    # tenant token bucket empty
SHED_TTL = "ttl"                  # queued past its deadline, pre-prefill
SHED_DRAINING = "draining"        # SIGTERM drain in progress

SHED_REASONS = (
    SHED_QUEUE_FULL, SHED_DEADLINE, SHED_RATE_LIMIT, SHED_TTL,
    SHED_DRAINING,
)


class AdmissionShedError(RuntimeError):
    """The front door refused this request before the engine saw it.

    Carries the machine-readable ``reason`` (one of ``SHED_REASONS``),
    the tenant it was accounted against, and — for retryable sheds — a
    drain-time estimate the servers surface as ``Retry-After``.
    """

    def __init__(
        self,
        reason: str,
        message: str,
        *,
        retry_after_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class EngineRestartError(RuntimeError):
    """The engine died mid-request and is being rebuilt by the
    supervisor (supervisor/supervisor.py).

    Raised to requests that had already emitted tokens when the engine
    died (replaying them would duplicate output) and to new arrivals
    while recovery is in progress with the front door disabled.  Always
    retryable: the pod expects to be SERVING again within
    ``retry_after_s`` — the wire mapping is UNAVAILABLE / 503 with a
    Retry-After hint, unlike terminal engine death (INTERNAL / 500).
    """

    def __init__(self, message: str, *, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class HandoffError(EngineRestartError):
    """A prefill→decode handoff exhausted its degradation ladder
    (docs/SCALING.md "Disaggregated roles"): capture failed (tier
    budget / gather failure on the prefill replica), the staged pages
    failed the validation read, no decode-capable replica is serving,
    or the resume itself raised.

    Subclasses ``EngineRestartError`` deliberately: the wire semantics
    are identical — UNAVAILABLE / 503 with a Retry-After hint, always
    retryable (the retry is cheap: the prompt's pages usually survive
    in the host tier and promote instead of recomputing) — so every
    existing classification site handles it by isinstance.  The
    distinct type exists for tests, logs, and the
    ``handoffs_total{outcome="fallback"}`` accounting.
    """


class CapacityError(RuntimeError):
    """Base for engine-side resource exhaustion (not a client error)."""


class KVPoolExhaustedError(CapacityError):
    """The KV page pool cannot hold even a single sequence's pages.

    Raised by the scheduler when preemption has no victims left; a
    sizing problem (pool too small for the workload), distinct from
    device OOM and from deliberate shedding.
    """


class DeviceOOMError(CapacityError):
    """Device (HBM) allocation failure, wrapped from the XLA runtime."""


# message markers that identify an XLA/PJRT out-of-memory failure; used
# ONLY by wrap_engine_error below — nothing else in the stack may
# classify by substring.  Deliberately narrow: a marker that can appear
# inside client-echoed text (request ids, adapter names) would
# misroute ordinary validation errors
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "RESOURCE EXHAUSTED",
    "out of memory",
    "Out of memory",
    "Allocation failure",
    "failed to allocate",
)

# client-input / programming error families that must never be
# reclassified as device OOM, whatever their message echoes
_NEVER_WRAP = (ValueError, TypeError, KeyError, AssertionError)


def wrap_engine_error(exc: BaseException) -> BaseException:
    """Boundary conversion: foreign engine-death exceptions → typed ones.

    Our own typed errors pass through untouched; an XLA/runtime error
    whose message matches an OOM marker becomes ``DeviceOOMError`` with
    the original chained as ``__cause__``.  Anything else is returned
    as-is (and will map to INTERNAL/500 downstream).
    """
    if isinstance(exc, (AdmissionShedError, CapacityError, EngineRestartError)):
        return exc
    if isinstance(exc, _NEVER_WRAP):
        return exc
    text = f"{type(exc).__name__}: {exc}"
    if any(marker in text for marker in _OOM_MARKERS):
        wrapped = DeviceOOMError(str(exc) or type(exc).__name__)
        wrapped.__cause__ = exc
        return wrapped
    return exc


def retry_after_seconds(estimate: Optional[float]) -> int:
    """The one Retry-After clamp both API surfaces use: a drain-time
    estimate becomes a 1–60s integer header/metadata value."""
    import math

    if estimate is None:
        return 1
    return int(min(60.0, max(1.0, math.ceil(estimate))))


@dataclasses.dataclass(frozen=True)
class ErrorDisposition:
    """How one error class goes on the wire, for both API surfaces.

    Engine-death handling is NOT encoded here: the gRPC server decides
    that from the live engine (``engine.errored``), not from the error
    class — a capacity error only means the engine died when the
    engine says so.
    """

    grpc_code: str       # grpc.StatusCode attribute name
    http_status: int
    err_type: str        # OpenAI-shaped error body "type"
    retry_after_s: Optional[float] = None


_SHED_DISPOSITIONS = {
    SHED_QUEUE_FULL: ("RESOURCE_EXHAUSTED", 429, "rate_limit_exceeded"),
    SHED_DEADLINE: ("RESOURCE_EXHAUSTED", 429, "rate_limit_exceeded"),
    SHED_RATE_LIMIT: ("RESOURCE_EXHAUSTED", 429, "rate_limit_exceeded"),
    SHED_TTL: ("DEADLINE_EXCEEDED", 408, "timeout_error"),
    SHED_DRAINING: ("UNAVAILABLE", 503, "service_unavailable"),
}


def classify(exc: BaseException) -> Optional[ErrorDisposition]:
    """Type-based status mapping; None means "not ours" (the caller's
    generic INTERNAL/500 path applies)."""
    exc = wrap_engine_error(exc)
    # adapter load/parse failures (missing adapter_config.json, rank >
    # --max-lora-rank, unknown target modules, pinned-full registry) are
    # CLIENT errors with actionable messages — INVALID_ARGUMENT / 400,
    # never a generic 500.  Lazy import: engine.lora pulls in jax, and
    # this module must stay importable standalone.
    try:
        from vllm_tgis_adapter_tpu.engine.lora import LoRAError
    except Exception:  # pragma: no cover — partial-install safety
        LoRAError = ()  # noqa: N806
    if LoRAError and isinstance(exc, LoRAError):
        return ErrorDisposition(
            grpc_code="INVALID_ARGUMENT",
            http_status=400,
            err_type="invalid_request_error",
        )
    if isinstance(exc, AdmissionShedError):
        code, status, err_type = _SHED_DISPOSITIONS.get(
            exc.reason, _SHED_DISPOSITIONS[SHED_QUEUE_FULL]
        )
        return ErrorDisposition(
            grpc_code=code,
            http_status=status,
            err_type=err_type,
            retry_after_s=exc.retry_after_s,
        )
    if isinstance(exc, EngineRestartError):
        # supervised restart in progress: the pod itself will be back —
        # retry HERE after the hint, unlike terminal engine death
        return ErrorDisposition(
            grpc_code="UNAVAILABLE",
            http_status=503,
            err_type="service_unavailable",
            retry_after_s=exc.retry_after_s,
        )
    if isinstance(exc, (KVPoolExhaustedError, DeviceOOMError)):
        # engine-side exhaustion (pool sizing / device HBM): retrying
        # this pod is pointless until the engine recovers or restarts
        return ErrorDisposition(
            grpc_code="RESOURCE_EXHAUSTED",
            http_status=503,
            err_type="server_error",
        )
    return None
