"""Front door: admission control, fair queuing, load shedding, drain.

The subsystem between the API surfaces (grpc/grpc_server.py, http.py)
and the engine's scheduler — see docs/FRONTDOOR.md for the admission
flow, tenant keying, flag reference, and drain sequence.
"""

from vllm_tgis_adapter_tpu.frontdoor.admission import FrontDoor
from vllm_tgis_adapter_tpu.frontdoor.drain import DrainCoordinator
from vllm_tgis_adapter_tpu.frontdoor.errors import (
    AdmissionShedError,
    CapacityError,
    DeviceOOMError,
    ErrorDisposition,
    KVPoolExhaustedError,
    classify,
    wrap_engine_error,
)
from vllm_tgis_adapter_tpu.frontdoor.fairness import (
    TokenBucket,
    WeightedFairQueue,
)

__all__ = [
    "AdmissionShedError",
    "CapacityError",
    "DeviceOOMError",
    "DrainCoordinator",
    "ErrorDisposition",
    "FrontDoor",
    "KVPoolExhaustedError",
    "TokenBucket",
    "WeightedFairQueue",
    "classify",
    "wrap_engine_error",
]
