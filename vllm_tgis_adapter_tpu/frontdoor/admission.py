"""The admission controller: everything between ``generate()`` and
``Scheduler.add``.

Before PR 4 both API surfaces handed every request straight to the
scheduler's unbounded ``waiting`` deque — under overload the queue (and
its detokenizers, FSMs, prompt buffers) grew until HBM or the event
loop keeled over, and a request could sit queued long past its own
deadline before ever reaching prefill.  S-LoRA (arXiv:2311.03285) shows
SLO-aware early-abort admission control is what keeps goodput up under
overload; this module implements that front door:

* **bounded queue** — ``--max-waiting-requests`` bounds parked +
  engine-waiting requests; past it, requests shed immediately with a
  Retry-After estimate instead of queuing into futility;
* **deadline-aware admission** — ``--admission-deadline`` sheds
  requests whose *estimated* queue-drain time already exceeds the SLO,
  using an observed token-throughput EWMA (seeded from the KV pool's
  token capacity before any observation, the ``resolve_num_blocks``
  budget math);
* **per-tenant WFQ + token buckets** (fairness.py) — requests park in
  a weighted fair queue keyed on the tenant header (falling back to
  adapter id) and are released to the engine in virtual-time order, a
  few at a time (the engine keeps only a small admission window so
  packed prefill still sees candidates but ordering stays ours);
* **queue TTLs** — a parked request whose deadline passes before
  prefill is shed (``shed`` flight-recorder event) instead of wasting
  prefill compute on an answer nobody is waiting for;
* **drain** — SIGTERM stops admission (``draining`` sheds) while
  in-flight requests finish (frontdoor/drain.py orchestrates).

Concurrency: everything here runs on the event loop; the pump task is
the only place entries leave the fair queue, and grants are accounted
(``_pending_grants``) so the engine window cannot be overshot between a
grant and the winner's ``add_request``.
"""

from __future__ import annotations

import asyncio
import time
from typing import TYPE_CHECKING, Callable, Optional

from vllm_tgis_adapter_tpu import metrics
from vllm_tgis_adapter_tpu.frontdoor.errors import (
    SHED_DEADLINE,
    SHED_DRAINING,
    SHED_QUEUE_FULL,
    SHED_RATE_LIMIT,
    SHED_TTL,
    AdmissionShedError,
)
from vllm_tgis_adapter_tpu.frontdoor.fairness import (
    TokenBucket,
    WeightedFairQueue,
)
from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.utils import spawn_task

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import FrontdoorConfig

logger = init_logger(__name__)

DEFAULT_TENANT = "default"

# throughput prior before any observed commit: assume the engine turns
# over one full KV pool of tokens in this many seconds.  Deliberately
# conservative — it only gates --admission-deadline sheds until the
# first real throughput sample lands (~1s of serving).
_CAPACITY_TURNOVER_S = 30.0

# tenant-label cardinality cap for the per-tenant token counter; the
# fair queue itself is not capped (tenant state is O(1) per tenant)
_MAX_TENANT_LABELS = 64

# liveness backstop: when entries are parked the pump re-checks at
# least this often even if every kick was missed
_PUMP_BACKSTOP_S = 0.5


class _ReplicaRate:
    """One replica's committed-token throughput observation state."""

    __slots__ = ("rate", "acc_tokens", "acc_since")

    def __init__(self) -> None:
        self.rate: Optional[float] = None
        self.acc_tokens = 0.0
        self.acc_since: Optional[float] = None


class FrontDoor:
    def __init__(
        self,
        config: "FrontdoorConfig",
        *,
        admit_window: int,
        room_fn: Callable[[int], bool],
        waiting_depth_fn: Callable[[], int],
        backlog_tokens_fn: Callable[[], float],
        kv_token_capacity_fn: Callable[[], float],
        serving_replicas_fn: Optional[
            Callable[[], "frozenset[int]"]
        ] = None,
        record_shed: Optional[Callable[..., None]] = None,
    ):
        """``room_fn(pending)`` — can the engine take another request
        given ``pending`` already-granted-but-not-yet-added ones;
        ``waiting_depth_fn`` — requests in the engines' waiting queues;
        ``backlog_tokens_fn`` — token backlog already inside the
        engines; ``kv_token_capacity_fn`` — pool size in tokens (the
        ``resolve_num_blocks`` budget), the throughput prior's base;
        ``serving_replicas_fn`` — indices of replicas currently serving
        (None = every replica that ever reported progress counts): the
        drain estimator sums PER-REPLICA throughput EWMAs over exactly
        this set, so one replica in supervised recovery subtracts its
        capacity instead of dragging a fleet-global average down and
        firing --admission-deadline sheds spuriously;
        ``record_shed(request_id, tenant, reason, **detail)`` — flight
        recorder hook."""
        self.config = config
        self.admit_window = max(1, admit_window)
        self._room_fn = room_fn
        self._waiting_depth_fn = waiting_depth_fn
        self._backlog_tokens_fn = backlog_tokens_fn
        self._kv_token_capacity_fn = kv_token_capacity_fn
        self._serving_replicas_fn = serving_replicas_fn
        self._record_shed = record_shed

        self._wfq = WeightedFairQueue(dict(config.tenant_weights))
        self._buckets: dict[str, TokenBucket] = {}
        self._pending_grants = 0
        self._pump_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        # explicit stop flag: Task.cancel() alone is unreliable here —
        # py3.10's asyncio.wait_for swallows a cancellation that lands
        # while the awaited event is already set (bpo-42130), which is
        # exactly the shutdown-right-after-wake interleaving
        self._stop = False
        self.draining = False
        # supervised recovery (supervisor/): paused means "hold, don't
        # shed" — new arrivals park, parked entries are not granted,
        # nothing is failed.  Distinct from draining, which refuses.
        self.paused = False
        self._drain_listeners: list[Callable[[], None]] = []
        self._tenant_labels: set[str] = set()

        # observed decode/prefill token throughput, PER REPLICA
        # (tokens/s EWMA each): the drain estimate sums the serving
        # replicas' rates, so a recovering replica subtracts capacity
        # cleanly instead of poisoning one global average
        self._rep_rates: dict[int, _ReplicaRate] = {}

        # lifetime counters (drain summary + tests)
        self.admitted_total = 0
        self.shed_total = 0

    # ---------------------------------------------------------------- intake

    async def acquire(
        self,
        *,
        request_id: str,
        tenant: Optional[str],
        tokens: float,
        deadline: Optional[float] = None,
    ) -> None:
        """Admit or shed one request.  Returns when the engine may take
        it (the caller MUST then call ``note_admitted()`` exactly once,
        success or failure); raises ``AdmissionShedError`` otherwise.

        ``tokens`` is the request's budget estimate (prompt + max new);
        ``deadline`` is the effective epoch-seconds SLO — the request's
        own deadline already tightened by ``--queue-ttl`` (the caller,
        AsyncLLMEngine.generate, stamps it at arrival so parked time
        counts against the TTL).
        """
        tenant = tenant or DEFAULT_TENANT
        cfg = self.config
        if self.draining:
            self._shed(
                request_id, tenant, SHED_DRAINING,
                "server is draining; not accepting new requests",
            )
        if cfg.max_waiting_requests > 0:
            # pending grants count: they are waiting requests that just
            # haven't reached add_request yet — omitting them lets
            # same-tick fast-path admissions overshoot the bound
            depth = (
                len(self._wfq)
                + self._waiting_depth_fn()
                + self._pending_grants
            )
            if depth >= cfg.max_waiting_requests:
                self._shed(
                    request_id, tenant, SHED_QUEUE_FULL,
                    f"waiting queue is full ({depth} >= "
                    f"{cfg.max_waiting_requests})",
                    retry_after_s=self._drain_estimate(tokens),
                )
        if cfg.admission_deadline_s > 0:
            est = self._drain_estimate(tokens)
            if est > cfg.admission_deadline_s:
                self._shed(
                    request_id, tenant, SHED_DEADLINE,
                    f"estimated queue drain {est:.1f}s exceeds the "
                    f"admission deadline {cfg.admission_deadline_s:.1f}s",
                    retry_after_s=est,
                )
        # the bucket is consumed LAST: a request shed on the bounds
        # above must not burn its tenant's rate budget
        wait = self._bucket(tenant).try_consume(tokens)
        if wait > 0:
            self._shed(
                request_id, tenant, SHED_RATE_LIMIT,
                f"tenant {tenant!r} exceeded its token rate limit",
                retry_after_s=wait,
            )

        self._note_tenant_tokens(tenant, tokens)
        # fast path: nothing queued ahead and the engine has room — no
        # pump round-trip, same latency as the pre-frontdoor hand-off.
        # Paused (engine recovery in flight) always parks: the request
        # must not reach add_request until the rebuilt engine is in.
        if (
            not self.paused
            and len(self._wfq) == 0
            and self._room_fn(self._pending_grants)
        ):
            self._pending_grants += 1
            self.admitted_total += 1
            return

        loop = asyncio.get_running_loop()
        future: asyncio.Future = loop.create_future()
        entry = self._wfq.push(
            tenant, tokens,
            {
                "request_id": request_id,
                "future": future,
                "deadline": deadline,
                "enqueued": time.time(),
                "tenant": tenant,
            },
        )
        self._ensure_pump()
        self._wake.set()
        self._refresh_gauges()
        try:
            await future
        except BaseException:
            if (
                future.done()
                and not future.cancelled()
                and future.exception() is None
            ):
                # the pump granted us (result set, _pending_grants
                # incremented) but cancellation landed before we
                # resumed — give the admission-window slot back or it
                # leaks until restart
                self.note_admitted()
            else:
                # still parked (or shed via the future): drop the entry
                self._wfq.cancel(entry)
            self._refresh_gauges()
            raise
        self.admitted_total += 1

    def note_admitted(self) -> None:
        """The granted request has reached (or failed) ``add_request``;
        its admission-window slot is the engine's problem now."""
        if self._pending_grants > 0:
            self._pending_grants -= 1
        self._wake.set()

    # ----------------------------------------------------------------- pump

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._stop = False  # an engine restarted after stop() pumps again
            self._pump_task = spawn_task(self._pump(), name="frontdoor-pump")

    async def _pump(self) -> None:
        """Release parked entries to the engine in WFQ order whenever
        the admission window has room; expire TTLs while waiting."""
        while not self._stop:
            timeout = None
            if len(self._wfq):
                timeout = _PUMP_BACKSTOP_S
                next_deadline = min(
                    (
                        e.payload["deadline"]
                        for e in self._wfq.entries()
                        if e.payload["deadline"] is not None
                    ),
                    default=None,
                )
                if next_deadline is not None:
                    timeout = min(
                        timeout, max(0.0, next_deadline - time.time())
                    )
            try:
                # tpulint: disable=TPL304(bpo-42130 is mitigated here: the loop re-checks _stop on every wake, timeout is bounded by _PUMP_BACKSTOP_S when work is queued, and stop() sets _wake so a swallowed timeout cancellation only delays one backstop interval)
                await asyncio.wait_for(self._wake.wait(), timeout)
            except asyncio.TimeoutError:
                pass
            if self._stop:
                return
            self._wake.clear()
            self._expire_ttls()
            while (
                not self.paused
                and len(self._wfq)
                and self._room_fn(self._pending_grants)
            ):
                entry = self._wfq.pop()
                if entry is None:
                    break
                future = entry.payload["future"]
                if future.done():
                    continue
                self._pending_grants += 1
                future.set_result(None)
            self._refresh_gauges()

    def _expire_ttls(self) -> None:
        now = time.time()
        for entry in self._wfq.entries():
            deadline = entry.payload["deadline"]
            if deadline is None or now < deadline:
                continue
            future = entry.payload["future"]
            self._wfq.cancel(entry)
            if future.done():
                continue
            queued_s = now - entry.payload["enqueued"]
            future.set_exception(
                self._shed_error(
                    entry.payload["request_id"], entry.tenant, SHED_TTL,
                    f"request spent {queued_s:.1f}s queued and passed "
                    "its deadline before prefill",
                    queued_s=round(queued_s, 3),
                )
            )

    def kick(self) -> None:
        """Engine progress signal (a commit retired, a request finished
        or aborted): re-check the admission window."""
        if len(self._wfq):
            self._wake.set()

    # ---------------------------------------------------------------- pause

    def pause(self) -> None:
        """Supervised engine recovery: hold all admission WITHOUT
        shedding — new arrivals park in the fair queue, parked entries
        keep their place, and nothing is granted until ``resume()``.
        Bounds and TTLs stay live (a full queue still sheds honestly;
        a deadline that expires while the engine rebuilds still expires).
        Idempotent."""
        self.paused = True

    def resume(self) -> None:
        """Recovery finished: grant again, oldest virtual-time first."""
        if not self.paused:
            return
        self.paused = False
        self._ensure_pump()
        self._wake.set()

    # ------------------------------------------------------------ estimator

    # an accumulation window older than this is an idle gap, not a
    # throughput observation — idle time must not read as low tok/s
    _RATE_WINDOW_MAX_S = 10.0

    def note_progress(self, tokens: float, replica: int = 0) -> None:
        """Feed one committed dispatch's token count into ``replica``'s
        throughput EWMA.  The drain estimate that prices
        --admission-deadline sheds sums these over the replicas the
        ``serving_replicas_fn`` hook currently reports.

        Per-replica windows make the idle reset trip more often than
        the old fleet-global accumulator (each replica sees 1/dp of the
        commits), so under very light traffic no rate may form and the
        estimate rests on the capacity prior.  Deliberate: the prior is
        the better predictor of under-backlog throughput anyway, and a
        real burst produces per-replica commits well inside the window,
        forming observed rates within a second or two."""
        state = self._rep_rates.get(replica)
        if state is None:
            state = self._rep_rates[replica] = _ReplicaRate()
        now = time.monotonic()
        if (
            state.acc_since is None
            or now - state.acc_since > self._RATE_WINDOW_MAX_S
        ):
            # first sample, or the window spans an idle period: start
            # fresh instead of decaying the EWMA toward zero
            state.acc_since = now
            state.acc_tokens = tokens
            self.kick()
            return
        state.acc_tokens += tokens
        dt = now - state.acc_since
        if dt >= 1.0:
            inst = state.acc_tokens / dt
            state.rate = (
                inst
                if state.rate is None
                else 0.7 * state.rate + 0.3 * inst
            )
            state.acc_tokens = 0.0
            state.acc_since = now
        self.kick()

    def forget_replica_rate(self, replica: int) -> None:
        """A replica was rebuilt: its pre-death throughput EWMA must not
        price the drain estimate the moment it re-admits (the rebuilt
        engine starts with an empty queue and a cold cache — counting
        the old rate would over-admit against --admission-deadline)."""
        self._rep_rates.pop(replica, None)

    def _serving_replicas(self) -> Optional["frozenset[int]"]:
        if self._serving_replicas_fn is None:
            return None
        try:
            return self._serving_replicas_fn()
        except Exception:  # pragma: no cover — estimator must not raise
            return None

    def _throughput(self) -> float:
        serving = self._serving_replicas()
        rates = [
            state.rate
            for idx, state in self._rep_rates.items()
            if state.rate is not None
            and state.rate > 0
            and (serving is None or idx in serving)
        ]
        if rates:
            return float(sum(rates))
        # prior before any observation: pool capacity over a
        # conservative turnover.  On a partial outage the capacity hook
        # excludes quiesced replicas; on a FULL outage it deliberately
        # falls back to the whole fleet — admission is paused then, and
        # full-fleet capacity is the right prior for the moment
        # recovery re-opens it
        capacity = max(self._kv_token_capacity_fn(), 1.0)
        return capacity / _CAPACITY_TURNOVER_S

    def _drain_estimate(self, extra_tokens: float = 0.0) -> float:
        """Seconds until a request admitted now would reach the device,
        assuming current backlog and observed throughput."""
        backlog = (
            self._backlog_tokens_fn()
            + self._wfq.queued_cost
            + extra_tokens
        )
        return backlog / self._throughput()

    # ---------------------------------------------------------------- drain

    def add_drain_listener(self, listener: Callable[[], None]) -> None:
        self._drain_listeners.append(listener)
        if self.draining:
            listener()

    def begin_drain(self) -> int:
        """Stop admitting; shed everything still parked (it never
        reached prefill — the client should retry against another
        replica).  Returns the number of parked requests shed.
        Idempotent."""
        if self.draining:
            return 0
        self.draining = True
        shed = 0
        for entry in self._wfq.entries():
            future = entry.payload["future"]
            self._wfq.cancel(entry)
            if future.done():
                continue
            shed += 1
            future.set_exception(
                self._shed_error(
                    entry.payload["request_id"], entry.tenant,
                    SHED_DRAINING,
                    "server is draining; not accepting new requests",
                )
            )
        for listener in self._drain_listeners:
            try:
                listener()
            except Exception:  # noqa: BLE001 — one listener must not block drain
                logger.exception("frontdoor drain listener failed")
        self._refresh_gauges()
        return shed

    def fail_all(self, exc: BaseException) -> None:
        """Engine death / shutdown: parked waiters must not hang."""
        for entry in self._wfq.entries():
            future = entry.payload["future"]
            self._wfq.cancel(entry)
            if not future.done():
                future.set_exception(exc)
        self._refresh_gauges()

    @property
    def parked(self) -> int:
        """Entries in the fair queue — O(1), for scrape-path callers."""
        return len(self._wfq)

    def note_external_shed(self) -> None:
        """A shed decided OUTSIDE the front door (the scheduler's
        queue-TTL path) still counts toward the lifetime total, so
        /debug/state and the metrics counter tell one story."""
        self.shed_total += 1

    async def shutdown(self) -> None:
        from vllm_tgis_adapter_tpu.engine.async_llm import EngineDeadError

        self.fail_all(EngineDeadError("engine is stopping"))
        if self._pump_task is not None:
            # stop flag first (see _stop) so the pump exits even when
            # the cancellation is swallowed by wait_for; cancel +
            # wake cover both suspension points
            self._stop = True
            self._wake.set()
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._pump_task = None

    # ------------------------------------------------------------ shed/metrics

    # tenant ids are client-controlled: bound the bucket map.  Evicting
    # oldest-created does not weaken the rate-limit model — an attacker
    # minting fresh tenant ids gets a fresh (full) bucket either way;
    # per-tenant limits only bind honest, stable tenant ids.
    _MAX_TENANT_BUCKETS = 1024

    def _bucket(self, tenant: str) -> TokenBucket:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            cfg = self.config
            burst = cfg.tenant_burst_tokens or (
                cfg.tenant_rate_tokens_per_s * 10.0
            )
            bucket = TokenBucket(cfg.tenant_rate_tokens_per_s, burst)
            while len(self._buckets) >= self._MAX_TENANT_BUCKETS:
                self._buckets.pop(next(iter(self._buckets)))
            self._buckets[tenant] = bucket
        return bucket

    def _tenant_label(self, tenant: str) -> str:
        if tenant in self._tenant_labels:
            return tenant
        if len(self._tenant_labels) >= _MAX_TENANT_LABELS:
            return "other"
        self._tenant_labels.add(tenant)
        return tenant

    def _note_tenant_tokens(self, tenant: str, tokens: float) -> None:
        try:
            metrics.frontdoor_tenant_tokens_total.labels(
                tenant=self._tenant_label(tenant)
            ).inc(tokens)
        except Exception:  # pragma: no cover — telemetry must not raise
            pass

    def _shed_error(
        self,
        request_id: str,
        tenant: str,
        reason: str,
        message: str,
        *,
        retry_after_s: Optional[float] = None,
        **detail,
    ) -> AdmissionShedError:
        """Build + account one shed (metrics, flight recorder, log)."""
        self.shed_total += 1
        try:
            metrics.frontdoor_sheds_total.labels(reason=reason).inc()
        except Exception:  # pragma: no cover
            pass
        if self._record_shed is not None:
            try:
                self._record_shed(
                    request_id, tenant, reason,
                    **(
                        {"retry_after_s": round(retry_after_s, 3)}
                        if retry_after_s is not None
                        else {}
                    ),
                    **detail,
                )
            except Exception:  # pragma: no cover
                logger.exception("shed recording failed")
        logger.warning(
            "shedding request %s (tenant=%s): %s [%s]",
            request_id, tenant, message, reason,
        )
        return AdmissionShedError(
            reason, message, retry_after_s=retry_after_s, tenant=tenant
        )

    def _shed(self, request_id, tenant, reason, message, **kwargs) -> None:  # noqa: ANN001, ANN003
        raise self._shed_error(
            request_id, tenant, reason, message, **kwargs
        )

    def _refresh_gauges(self) -> None:
        try:
            metrics.frontdoor_queue_depth.set(len(self._wfq))
            oldest = min(
                (e.payload["enqueued"] for e in self._wfq.entries()),
                default=None,
            )
            metrics.frontdoor_queue_age_seconds.set(
                max(0.0, time.time() - oldest)
                if oldest is not None
                else 0.0
            )
        except Exception:  # pragma: no cover
            pass

    def refresh_gauges(self) -> None:
        """Scrape-time hook (AsyncLLMEngine.refresh_engine_gauges)."""
        self._refresh_gauges()

    def debug_state(self) -> dict:
        """Front-door section of the engine's /debug/state snapshot."""
        entries = self._wfq.entries()
        now = time.time()
        return {
            "draining": self.draining,
            "paused": self.paused,
            "parked": len(entries),
            "pending_grants": self._pending_grants,
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "throughput_tok_per_s": round(self._throughput(), 1),
            "throughput_by_replica": {
                str(idx): round(state.rate, 1)
                for idx, state in sorted(self._rep_rates.items())
                if state.rate is not None
            },
            "oldest_age_s": round(
                max(
                    (now - e.payload["enqueued"] for e in entries),
                    default=0.0,
                ),
                3,
            ),
            "tenants": sorted({e.tenant for e in entries}),
        }
