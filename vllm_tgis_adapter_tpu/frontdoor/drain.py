"""Graceful drain: SIGTERM → stop admitting → finish in-flight → exit.

Before PR 4 SIGTERM cancelled the server tasks outright: the engine
step loops died mid-decode and every in-flight generation was lost.
Kubernetes sends SIGTERM, waits ``terminationGracePeriodSeconds``, then
SIGKILLs — this coordinator uses that window properly:

1. flip health (gRPC ``DRAINING``, HTTP ``/health`` → 503) so
   orchestrators stop routing new traffic at the pod;
2. stop admitting (the front door sheds with ``draining`` /
   UNAVAILABLE; parked-but-not-prefilled requests are shed too — their
   clients retry against a healthy replica);
3. let requests already inside the engine finish, bounded by
   ``--drain-grace``;
4. checkpoint the termination log with the drain outcome and release
   the server loop to shut down normally.

A second SIGTERM during drain forces immediate shutdown (the operator
means it).
"""

from __future__ import annotations

import asyncio
import os
import signal
import time
from typing import TYPE_CHECKING, Optional

from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.utils import spawn_task, write_termination_log

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine

logger = init_logger(__name__)

_POLL_S = 0.05


class DrainCoordinator:
    def __init__(
        self,
        engine: "AsyncLLMEngine",
        *,
        grace_s: float = 30.0,
        shutdown_event: Optional[asyncio.Event] = None,
        termination_log_dir: Optional[str] = None,
    ):
        self.engine = engine
        self.grace_s = grace_s
        self.shutdown_event = shutdown_event or asyncio.Event()
        self._termination_log_dir = termination_log_dir or os.getenv(
            "TERMINATION_LOG_DIR", "/dev/termination-log"
        )
        self._task: Optional[asyncio.Task] = None
        self._parked_shed = 0
        self.started = False
        self.summary: Optional[dict] = None

    # ------------------------------------------------------------- lifecycle

    def install(self, loop: asyncio.AbstractEventLoop) -> bool:
        """Register the SIGTERM handler; False where unsupported
        (non-unix / non-main-thread loops)."""
        try:
            loop.add_signal_handler(signal.SIGTERM, self.begin)
        except (NotImplementedError, RuntimeError, ValueError):
            logger.info(
                "SIGTERM drain handler not installed "
                "(unsupported on this platform/loop)"
            )
            return False
        return True

    def uninstall(self, loop: asyncio.AbstractEventLoop) -> None:
        try:
            loop.remove_signal_handler(signal.SIGTERM)
        except (NotImplementedError, RuntimeError, ValueError):
            pass

    def begin(self) -> None:
        """Start the drain (signal-handler-safe, idempotent); a repeat
        call while draining forces immediate shutdown."""
        if self.started:
            logger.warning(
                "second drain request: forcing immediate shutdown"
            )
            self.shutdown_event.set()
            return
        self.started = True
        # lifecycle alignment (supervisor/lifecycle.py): healthcheck /
        # debug surfaces read 'draining' from the same state machine the
        # supervisor drives; don't clobber a terminal 'dead'
        from vllm_tgis_adapter_tpu.supervisor.lifecycle import (
            LIFECYCLE_DEAD,
            LIFECYCLE_DRAINING,
        )

        if getattr(self.engine, "lifecycle", None) not in (
            None, LIFECYCLE_DEAD,
        ):
            self.engine.lifecycle = LIFECYCLE_DRAINING
        frontdoor = getattr(self.engine, "frontdoor", None)
        if frontdoor is None:
            # --disable-frontdoor: with no admission gate there is
            # nothing to stop and no DRAINING health to flip — waiting
            # out the grace window would keep accepting requests only
            # to kill them at its end.  Honor the escape hatch's
            # pre-PR4 contract: immediate shutdown.
            logger.warning(
                "SIGTERM with the front door disabled: no drain "
                "possible, shutting down immediately"
            )
            self.summary = {"frontdoor": "disabled"}
            self.shutdown_event.set()
            return
        # stop admission SYNCHRONOUSLY: from the moment the signal
        # handler returns, no new request can slip past the front door
        self._parked_shed = frontdoor.begin_drain()
        self._task = spawn_task(
            self._drain(), name="frontdoor-drain",
            loop=asyncio.get_event_loop(),
        )

    # ----------------------------------------------------------------- drain

    def _in_flight(self) -> int:
        engine_resident = sum(
            rep.engine.scheduler.num_unfinished
            for rep in self.engine._replicas  # noqa: SLF001 — coordinator owns this view
        )
        frontdoor = getattr(self.engine, "frontdoor", None)
        granted = (
            frontdoor._pending_grants  # noqa: SLF001
            if frontdoor is not None
            else 0
        )
        # registered output queues count too: the engine may be done
        # generating while a (slow) client is still consuming its final
        # frames — tearing the servers down then would truncate the
        # very responses the drain promised to finish
        undelivered = len(self.engine._queues)  # noqa: SLF001
        return engine_resident + granted + undelivered

    async def _drain(self) -> None:
        t0 = time.monotonic()
        shed_parked = self._parked_shed
        in_flight0 = self._in_flight()
        logger.info(
            "drain started: %d in-flight requests to finish "
            "(grace %.0fs), %d parked requests shed",
            in_flight0, self.grace_s, shed_parked,
        )
        deadline = t0 + max(0.0, self.grace_s)
        while self._in_flight() > 0 and time.monotonic() < deadline:
            await asyncio.sleep(_POLL_S)
        remaining = self._in_flight()
        elapsed = time.monotonic() - t0
        self.summary = {
            "drained_s": round(elapsed, 3),
            "in_flight_at_sigterm": in_flight0,
            "parked_shed": shed_parked,
            "unfinished_at_exit": remaining,
        }
        msg = (
            f"graceful drain {'complete' if remaining == 0 else 'TIMED OUT'}: "
            f"{in_flight0} in-flight finished in {elapsed:.1f}s, "
            f"{shed_parked} parked shed, {remaining} unfinished at exit"
        )
        (logger.info if remaining == 0 else logger.warning)("%s", msg)
        # checkpoint the outcome where k8s post-mortems read it; on the
        # happy path this is the LAST write (the process exits cleanly)
        write_termination_log(msg, self._termination_log_dir)
        # one settle tick for the transports to flush the final frames
        # already handed to the sockets
        await asyncio.sleep(0.25)
        self.shutdown_event.set()
