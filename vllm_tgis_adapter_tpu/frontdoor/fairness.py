"""Per-tenant weighted fair queuing + token-bucket rate limiting.

Pure data structures (no asyncio, injectable clocks) so the fairness
math is unit-testable in isolation; ``admission.FrontDoor`` owns the
concurrency around them.

The fair queue is classic virtual-time WFQ over *token* cost, not
request count: a tenant submitting 4k-token prompts consumes its share
4k tokens at a time, so a tenant of equal weight sending 32-token
prompts still gets through.  Heterogeneous-adapter serving work
(PAPERS.md, arXiv:2511.22880) motivates exactly this: adapters/tenants
sharing one engine must not be starved by a heavyweight neighbor.
"""

from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Any, Callable, Mapping, Optional


class TokenBucket:
    """Standard token bucket: ``rate`` tokens/s refill, ``burst`` cap.

    ``try_consume(n)`` returns 0.0 on success or the seconds until the
    bucket would hold ``n`` tokens (the Retry-After hint).  A request
    larger than the burst can never succeed; the returned wait is still
    finite so callers shed it with a truthful (if optimistic) hint.
    """

    def __init__(
        self,
        rate: float,
        burst: float,
        now: Callable[[], float] = time.monotonic,
    ):
        self.rate = rate
        self.burst = max(burst, 1.0)
        self._now = now
        self._tokens = self.burst
        self._last = now()

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_consume(self, n: float) -> float:
        if self.rate <= 0:
            return 0.0  # rate limiting disabled
        self._refill()
        if n <= self._tokens:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / self.rate


@dataclasses.dataclass
class QueueEntry:
    tenant: str
    cost: float
    payload: Any
    tag: float = 0.0     # virtual finish time
    seq: int = 0         # arrival tiebreak
    cancelled: bool = False
    popped: bool = False  # left the queue via pop(); cancel() no-ops


class WeightedFairQueue:
    """Virtual-time WFQ: pop order interleaves tenants by weight.

    Each tenant's entries get virtual finish tags
    ``start + cost / weight`` where ``start`` continues the tenant's
    previous tag (per-tenant FIFO) but never falls behind the global
    virtual time (an idle tenant doesn't bank unbounded credit).  Pop
    returns the smallest tag; ties break by arrival order.  Removal is
    lazy (``cancelled`` flag) so client disconnects are O(1).
    """

    def __init__(
        self,
        weights: Optional[Mapping[str, float]] = None,
        default_weight: float = 1.0,
    ):
        self._weights = dict(weights or {})
        self._default_weight = max(default_weight, 1e-9)
        self._heap: list[tuple[float, int, QueueEntry]] = []
        self._last_tag: dict[str, float] = {}
        self._virtual_time = 0.0
        self._seq = 0
        self._live = 0
        self._live_cost = 0.0

    def weight_of(self, tenant: str) -> float:
        w = self._weights.get(tenant, self._default_weight)
        return max(float(w), 1e-9)

    # cap on remembered per-tenant finish tags: the tenant id comes
    # from a client-controlled header, so the dict must not grow
    # unboundedly.  Tags at or below the virtual time carry no
    # information (start = max(virtual_time, last_tag)), so idle
    # tenants prune losslessly.
    _MAX_TENANT_TAGS = 1024

    def push(self, tenant: str, cost: float, payload: Any) -> QueueEntry:
        cost = max(float(cost), 1.0)
        if len(self._last_tag) > self._MAX_TENANT_TAGS:
            self._last_tag = {
                t: tag
                for t, tag in self._last_tag.items()
                if tag > self._virtual_time
            }
        start = max(
            self._virtual_time, self._last_tag.get(tenant, 0.0)
        )
        entry = QueueEntry(tenant=tenant, cost=cost, payload=payload)
        entry.tag = start + cost / self.weight_of(tenant)
        entry.seq = self._seq
        self._seq += 1
        self._last_tag[tenant] = entry.tag
        heapq.heappush(self._heap, (entry.tag, entry.seq, entry))
        self._live += 1
        self._live_cost += cost
        return entry

    def cancel(self, entry: QueueEntry) -> None:
        if not entry.cancelled and not entry.popped:
            entry.cancelled = True
            self._live -= 1
            self._live_cost -= entry.cost

    def pop(self) -> Optional[QueueEntry]:
        while self._heap:
            tag, _, entry = heapq.heappop(self._heap)
            if entry.cancelled:
                continue
            entry.popped = True
            self._virtual_time = max(self._virtual_time, tag)
            self._live -= 1
            self._live_cost -= entry.cost
            return entry
        return None

    def entries(self) -> list[QueueEntry]:
        """Live entries, UNORDERED — O(n).  Every caller (TTL scans,
        drain shedding, gauge refresh) aggregates or acts on all
        entries; pop order comes only from ``pop()``."""
        return [e for _, _, e in self._heap if not e.cancelled]

    def __len__(self) -> int:
        return self._live

    @property
    def queued_cost(self) -> float:
        """Total token cost of live entries (drain-estimate input)."""
        return max(self._live_cost, 0.0)
