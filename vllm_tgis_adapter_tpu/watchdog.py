"""Stall watchdog: turns a silent step-loop hang into a diagnostic dump.

The async engine's step loops beat this watchdog on every iteration
(``engine/async_llm.py``).  When a replica has unfinished work but its
loop has not beaten for ``deadline_s`` (default 120 s), the watchdog
emits one full diagnostic snapshot — scheduler queues with request ages,
KV allocator occupancy, the in-flight batch plan, compile-tracker state,
and the last N flight-recorder events — to three places at once:

* the log (ERROR, single line of JSON so log pipelines keep it intact),
* the Kubernetes termination log (the stall usually precedes a liveness
  kill; the dump must survive the pod),
* a timestamped JSON file under ``--dump-dir`` (when configured).

Compile-awareness: XLA/Mosaic compiles on TPU run 20-40 s *each* and a
cold bucket sweep runs several back to back, all of which legitimately
starves the heartbeat.  While the compile tracker reports a tracked
dispatch in flight the deadline is suspended — up to
``compile_grace_s`` (default 600 s), after which a "compile" that never
returns is treated as the hang it is.

One dump per stall episode: after firing, the watchdog re-arms only
once a fresh heartbeat proves the loop recovered.  File writes happen in
``asyncio.to_thread`` so the dump path itself can never block the event
loop it is diagnosing (tpulint TPL302).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Callable, Optional

from vllm_tgis_adapter_tpu import compile_tracker, metrics
from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.utils import spawn_task, write_termination_log

logger = init_logger(__name__)

DEFAULT_DEADLINE_S = 120.0
DEFAULT_COMPILE_GRACE_S = 600.0


class StallWatchdog:
    """Heartbeat-fed watchdog task over one engine's step loops.

    ``snapshot_fn`` builds the diagnostic dict (the shared serializer in
    ``flight_recorder.py`` via ``AsyncLLMEngine.debug_state``);
    ``active_fn`` reports whether any work is in flight (an idle engine
    never beats, and never stalls); ``beat()`` is called by the step
    loops (and on request submission, so a dead loop gets exactly one
    deadline of grace from the moment work arrives).
    """

    def __init__(
        self,
        *,
        snapshot_fn: Callable[[], dict],
        active_fn: Callable[[], bool],
        age_fn: Optional[Callable[[], float]] = None,
        deadline_s: float = DEFAULT_DEADLINE_S,
        compile_grace_s: float = DEFAULT_COMPILE_GRACE_S,
        dump_dir: Optional[str] = None,
        check_interval_s: Optional[float] = None,
        termination_log: Optional[str] = None,
        action: str = "snapshot",
        restart_fn: Optional[Callable[[], None]] = None,
    ):
        if action not in ("snapshot", "restart"):
            raise ValueError(
                f"--watchdog-action must be 'snapshot' or 'restart' "
                f"(got {action!r})"
            )
        # detection → action wiring (--watchdog-action): 'snapshot'
        # preserves the PR-3 behavior (diagnose only); 'restart' hands
        # the stall to restart_fn (the engine supervisor) AFTER the
        # snapshot has been written — the evidence always outlives the
        # restart that destroys the stalled state
        self.action = action
        self._restart_fn = restart_fn
        self.deadline_s = deadline_s
        self.compile_grace_s = compile_grace_s
        self.dump_dir = dump_dir
        self.check_interval_s = check_interval_s or max(
            1.0, min(deadline_s / 4, 15.0)
        )
        self._snapshot_fn = snapshot_fn
        self._active_fn = active_fn
        # age_fn overrides the built-in single heartbeat: a dp fleet
        # reports max(age over replicas with unfinished work), so one
        # stalled replica fires even while its siblings beat happily
        self._age_fn = age_fn
        self._termination_log = termination_log or os.getenv(
            "TERMINATION_LOG_DIR", "/dev/termination-log"
        )
        self._last_beat = time.monotonic()
        self._fired = False  # one dump per stall episode
        self.stalls = 0  # fired count (the counter metric keeps history)
        self.last_dump_path: Optional[str] = None
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------ heartbeat

    def beat(self) -> None:
        self._last_beat = time.monotonic()

    def heartbeat_age(self) -> float:
        if self._age_fn is not None:
            return self._age_fn()
        return time.monotonic() - self._last_beat

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._task is None:
            self.beat()  # boot counts as a beat: deadline starts now
            self._task = spawn_task(self.run(), name="stall-watchdog")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            await self.check()

    # ----------------------------------------------------------- detection

    async def check(self) -> Optional[dict]:
        """One watchdog tick; returns the snapshot if a stall fired."""
        age = self.heartbeat_age()
        metrics.watchdog_last_heartbeat_age_seconds.set(age)
        if age <= self.deadline_s:
            self._fired = False  # loop recovered: re-arm
            return None
        if not self._active_fn():
            return None
        inflight = compile_tracker.inflight_dispatch()
        if inflight is not None and inflight[1] < self.compile_grace_s:
            # a tracked dispatch (possibly a 20-40s Mosaic compile, or a
            # serial warmup sweep of them) is still making the runtime
            # do work — suspend the stall verdict until the grace runs out
            logger.debug(
                "watchdog suspended: dispatch %s in flight for %.1fs",
                inflight[0], inflight[1],
            )
            return None
        if self._fired:
            return None  # already dumped this episode
        self._fired = True
        return await self.fire(age)

    async def fire(self, age: float) -> dict:
        """Emit the diagnostic snapshot everywhere it can outlive the pod."""
        self.stalls += 1
        metrics.watchdog_stalls_total.inc()
        try:
            snapshot = self._snapshot_fn()
        except Exception:  # noqa: BLE001 — a broken engine is the expected case
            logger.exception("watchdog snapshot collection failed")
            snapshot = {"error": "snapshot collection failed"}
        snapshot = {
            "reason": "step-loop heartbeat stall",
            "heartbeat_age_s": round(age, 3),
            "deadline_s": self.deadline_s,
            "dumped_at": time.time(),
            **snapshot,
        }
        blob = json.dumps(snapshot, default=str)
        logger.error(
            "engine step loop stalled (no heartbeat for %.1fs > %.1fs "
            "deadline); diagnostic snapshot: %s", age, self.deadline_s, blob,
        )
        dump_ref = "logs"
        if self.dump_dir:
            path = os.path.join(
                self.dump_dir,
                f"stall-{time.strftime('%Y%m%dT%H%M%S')}-{self.stalls}.json",
            )

            def _write() -> None:
                os.makedirs(self.dump_dir, exist_ok=True)
                with open(path, "w") as f:
                    f.write(blob)

            try:
                await asyncio.to_thread(_write)
                self.last_dump_path = path
                dump_ref = path
                logger.error("stall snapshot written to %s", path)
            except Exception:  # noqa: BLE001 — the log copy already exists
                logger.exception("failed to write stall dump to %s", path)
        summary = (
            f"engine step loop stalled: no heartbeat for {age:.1f}s "
            f"(deadline {self.deadline_s:.0f}s); see {dump_ref} for the "
            "full snapshot"
        )
        await asyncio.to_thread(
            write_termination_log, summary, self._termination_log
        )
        if self.action == "restart" and self._restart_fn is not None:
            # snapshot first, restart second: the dump above captured
            # the stalled state this restart is about to tear down
            logger.error(
                "watchdog action=restart: requesting supervised engine "
                "restart for the stalled step loop"
            )
            try:
                self._restart_fn()
            except Exception:  # noqa: BLE001 — the dump already happened
                logger.exception("watchdog restart request failed")
        return snapshot
