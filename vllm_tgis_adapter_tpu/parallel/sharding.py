"""Sharding rules: llama param pytree + paged KV cache onto the mesh.

Megatron-style tensor parallelism expressed as ``PartitionSpec`` leaves —
the XLA SPMD partitioner turns these into the same comm pattern the
reference stack gets from hand-written NCCL calls inside vLLM (one
all-reduce after attention-out and one after mlp-down per layer):

* wq/wk/wv ``[d, H·Dh]``: column-parallel (heads split across tp)
* wo ``[H·Dh, d]``: row-parallel → psum of partial sums
* w_gate/w_up ``[d, f]``: column-parallel; w_down ``[f, d]``: row-parallel
* embed ``[V, d]``: vocab-parallel; lm_head ``[d, V]``: column-parallel
  (logits arrive vocab-sharded; the sampler's reductions gather them)
* KV cache ``[L, slots, Hkv, Dh]``: head-sharded — each tp shard holds the
  pages for its own kv heads, so paged reads/writes are shard-local
* norms / biases on the hidden dim: replicated

No activation specs are needed: annotating the params is enough for the
partitioner to propagate Megatron sharding through the whole step fn.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vllm_tgis_adapter_tpu.parallel.mesh import TP_AXIS

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import ModelConfig


def validate_tp_divisibility(config: "ModelConfig", tp: int) -> None:
    """Fail fast (like vLLM's engine-boot check) when tp can't split the model."""
    problems = []
    if config.num_heads % tp:
        problems.append(f"num_heads={config.num_heads}")
    if config.num_kv_heads % tp:
        problems.append(f"num_kv_heads={config.num_kv_heads}")
    expert_parallel = config.num_experts > 0 and config.num_experts % tp == 0
    if not expert_parallel and config.intermediate_size % tp:
        # MoE models whose expert count divides tp shard the EXPERT axis
        # instead of the ffn dim, so the ffn constraint doesn't apply
        problems.append(f"intermediate_size={config.intermediate_size}")
    if config.vocab_size % tp:
        problems.append(f"vocab_size={config.vocab_size}")
    if problems:
        raise ValueError(
            f"tensor_parallel_size={tp} does not divide "
            + ", ".join(problems)
        )


_LAYER_SPECS = {
    "input_norm": P(None),
    "input_norm_bias": P(None),
    "post_attn_norm": P(None),
    "post_attn_norm_bias": P(None),
    "wq": P(None, TP_AXIS),
    "wk": P(None, TP_AXIS),
    "wv": P(None, TP_AXIS),
    "wo": P(TP_AXIS, None),
    "w_gate": P(None, TP_AXIS),
    "w_up": P(None, TP_AXIS),
    "w_down": P(TP_AXIS, None),
    "bq": P(TP_AXIS),
    "bk": P(TP_AXIS),
    "bv": P(TP_AXIS),
    # qwen3 per-head-dim q/k norms: [head_dim] vectors, replicated
    "q_norm": P(None),
    "k_norm": P(None),
    # row-parallel output biases: replicated, added once after the psum
    "bo": P(None),
    "b_down": P(None),
    # column-parallel fc1 bias follows its weight's tp split
    "b_up": P(TP_AXIS),
    "router": P(None, None),
}

# mixtral MoE expert stacks [E, ...]: EXPERT-parallel when tp divides E
# (each shard computes its local experts over all tokens; the dense
# routing sum becomes a psum the partitioner merges with the layer's
# existing output all-reduce), else Megatron-style within-expert ffn
# sharding on the trailing dims
_EXPERT_EP_SPECS = {
    "experts_gate": P(TP_AXIS, None, None),
    "experts_up": P(TP_AXIS, None, None),
    "experts_down": P(TP_AXIS, None, None),
}
_EXPERT_FFN_SPECS = {
    "experts_gate": P(None, None, TP_AXIS),
    "experts_up": P(None, None, TP_AXIS),
    "experts_down": P(None, TP_AXIS, None),
}


def llama_param_specs(params: dict, tp: int = 1) -> dict:
    """PartitionSpec pytree matching models/llama.py's param layout."""
    # emit a spec for exactly the keys present: pipeline stages carry
    # partial trees (embed on stage 0 only, final norm / lm_head on the
    # last), and tree.map requires identical dict structure
    top_specs = {
        "embed": P(TP_AXIS, None),
        "final_norm": P(None),
        "final_norm_bias": P(None),
        "embed_norm": P(None),
        "embed_norm_bias": P(None),
        # tiny table (max_len rows); replicate rather than shard
        "pos_embed": P(None, None),
        "lm_head": P(None, TP_AXIS),
    }
    specs: dict = {
        name: top_specs[name] for name in params if name != "layers"
    }

    def layer_spec(layer: dict) -> dict:
        expert_specs = _EXPERT_FFN_SPECS
        if "experts_gate" in layer:
            num_experts = layer["experts_gate"].shape[0]
            if tp > 1 and num_experts % tp == 0:
                expert_specs = _EXPERT_EP_SPECS

        def spec_of(name: str) -> P:
            # weight-only int8 leaves (engine/weights.py): the q8 matrix
            # keeps its source weight's spec; the [out] scale vector
            # follows the weight's out axis (tp-split for column-parallel
            # weights, replicated for row-parallel ones)
            if name.endswith("_q8"):
                name = name[: -len("_q8")]
            elif name.endswith("_scale"):
                base = _LAYER_SPECS[name[: -len("_scale")]]
                return P(base[1] if len(base) > 1 else None)
            return expert_specs.get(name) or _LAYER_SPECS[name]

        return {name: spec_of(name) for name in layer}

    specs["layers"] = [layer_spec(layer) for layer in params["layers"]]
    return specs


def shard_llama_params(mesh: Mesh, params: dict) -> dict:
    """device_put every leaf onto the mesh with its Megatron spec.

    (tree.map uses ``params``' structure, so the PartitionSpec leaves of
    ``specs`` are passed through whole — they are never flattened even
    though PartitionSpec subclasses tuple.)
    """
    specs = llama_param_specs(params, tp=mesh.shape[TP_AXIS])
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )


# HF checkpoint-name → spec for shard-on-load (engine/weights.py PlaceFn):
# names seen AFTER the loader's transpose to [in, out] orientation.
_HF_NAME_SPECS = (
    ("embed_tokens.weight", P(TP_AXIS, None)),
    ("lm_head.weight", P(None, TP_AXIS)),
    ("q_proj.weight", P(None, TP_AXIS)),
    ("k_proj.weight", P(None, TP_AXIS)),
    ("v_proj.weight", P(None, TP_AXIS)),
    ("o_proj.weight", P(TP_AXIS, None)),
    ("gate_proj.weight", P(None, TP_AXIS)),
    ("up_proj.weight", P(None, TP_AXIS)),
    ("down_proj.weight", P(TP_AXIS, None)),
    ("q_proj.bias", P(TP_AXIS)),
    ("k_proj.bias", P(TP_AXIS)),
    ("v_proj.bias", P(TP_AXIS)),
    # mixtral per-expert FFNs (w1=gate, w3=up: column-parallel; w2=down:
    # row-parallel after the loader's transpose).  Sharding each expert
    # tensor as it is read keeps the anti-OOM invariant for the model
    # family with the LARGEST weights; shard_llama_params may later
    # redistribute the stacked [E, ...] arrays onto the expert axis (EP)
    ("w1.weight", P(None, TP_AXIS)),
    ("w3.weight", P(None, TP_AXIS)),
    ("w2.weight", P(TP_AXIS, None)),
    # OPT lineage: out_proj/fc1/fc2 + biases, learned position table
    ("out_proj.weight", P(TP_AXIS, None)),
    ("out_proj.bias", P()),
    ("fc1.weight", P(None, TP_AXIS)),
    ("fc1.bias", P(TP_AXIS)),
    ("fc2.weight", P(TP_AXIS, None)),
    ("fc2.bias", P()),
    ("embed_positions.weight", P()),
    # gpt_neox lineage: attention.dense (row-parallel out), h_to_4h
    # (column) / 4h_to_h (row) MLP, vocab-parallel embed_in, and
    # embed_out placed post-transpose like lm_head
    ("attention.dense.weight", P(TP_AXIS, None)),
    ("attention.dense.bias", P()),
    ("dense_h_to_4h.weight", P(None, TP_AXIS)),
    ("dense_h_to_4h.bias", P(TP_AXIS)),
    ("dense_4h_to_h.weight", P(TP_AXIS, None)),
    ("dense_4h_to_h.bias", P()),
    ("embed_in.weight", P(TP_AXIS, None)),
    ("embed_out.weight", P(None, TP_AXIS)),
    # bloom: vocab-parallel embeddings, replicated final norm (the
    # generic norm.weight/bias suffixes catch the layernorms)
    ("word_embeddings.weight", P(TP_AXIS, None)),
    ("ln_f.weight", P(None)),
    ("ln_f.bias", P(None)),
    # gpt2: Conv1D (already [in, out]); attn/mlp c_proj are both
    # row-parallel, c_fc column-parallel, wte vocab-parallel, wpe + the
    # ln_1/ln_2 norms replicate via the default P()
    ("c_proj.weight", P(TP_AXIS, None)),
    ("c_proj.bias", P()),
    ("c_fc.weight", P(None, TP_AXIS)),
    ("c_fc.bias", P(TP_AXIS)),
    ("wte.weight", P(TP_AXIS, None)),
    ("norm.weight", P(None)),
    ("norm.bias", P(None)),
    ("layernorm.weight", P(None)),
)


def hf_name_spec(name: str) -> P:
    for suffix, spec in _HF_NAME_SPECS:
        if name.endswith(suffix):
            return spec
    return P()


def make_place_fn(mesh: Mesh):
    """PlaceFn for the weight loader: shard each tensor onto the mesh as it
    is read, so no device ever materialises the full unsharded model
    (70B-class models exceed one chip's HBM — sharding after a full load
    would OOM device 0)."""

    def place(name: str, x: jax.Array) -> jax.Array:
        return jax.device_put(x, NamedSharding(mesh, hf_name_spec(name)))

    return place


def cache_sharding(mesh: Mesh) -> NamedSharding:
    """KV cache ``[L, Hkv, slots, Dh]``: shard the kv-head axis on tp."""
    return NamedSharding(mesh, P(None, TP_AXIS, None, None))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Replicated placement for host-built step inputs (token ids, tables).

    Data-parallel batch sharding will split these on the dp axis; with a
    single engine replica they are replicated so every tp shard sees the
    full batch.
    """
    return NamedSharding(mesh, P())
