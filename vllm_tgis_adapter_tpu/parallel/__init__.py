"""Distributed layer: device meshes, sharding rules, multi-host init.

TPU-native replacement for the NCCL/Ray/MP distribution stack beneath the
reference adapter (SURVEY.md §2.4): there is no process-group runtime to
write — collectives are XLA ops emitted by the SPMD partitioner under a
``jax.sharding.Mesh`` — but mesh construction, parameter/KV-cache layout,
and multi-host initialisation are ours and live here.
"""

from vllm_tgis_adapter_tpu.parallel.mesh import (
    MeshAxes,
    build_mesh,
    initialize_multihost,
    mesh_from_parallel_config,
)
from vllm_tgis_adapter_tpu.parallel.sharding import (
    cache_sharding,
    data_sharding,
    llama_param_specs,
    make_place_fn,
    shard_llama_params,
    validate_tp_divisibility,
)

__all__ = [
    "MeshAxes",
    "build_mesh",
    "initialize_multihost",
    "mesh_from_parallel_config",
    "cache_sharding",
    "data_sharding",
    "llama_param_specs",
    "make_place_fn",
    "shard_llama_params",
    "validate_tp_divisibility",
]
