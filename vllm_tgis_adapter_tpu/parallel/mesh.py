"""Device-mesh construction and multi-host initialisation.

The reference stack distributes with NCCL process groups spawned by vLLM
(one worker per TP rank; `--num-shard` → ``tensor_parallel_size``,
reference tgis_utils/args.py:139-142).  On TPU the equivalent is a
single-controller ``jax.sharding.Mesh`` whose axes ride the ICI fabric;
collectives (psum/all-gather/reduce-scatter/ppermute) are inserted by the
XLA SPMD partitioner from sharding annotations, so this module only owns
mesh geometry and host-process bring-up.

Axis convention (outermost → innermost, matching ICI locality: the tp axis
is innermost so its all-reduces ride the fastest links):

* ``dp``  — data parallel / replica axis (DCN across slices later)
* ``sp``  — sequence/context parallel axis (ring attention, long context)
* ``tp``  — tensor parallel axis (Megatron-style sharded matmuls)
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

from vllm_tgis_adapter_tpu.logging import init_logger

logger = init_logger(__name__)

DP_AXIS = "dp"
SP_AXIS = "sp"
TP_AXIS = "tp"
AXIS_NAMES = (DP_AXIS, SP_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """Logical parallelism degrees for one engine instance."""

    data_parallel_size: int = 1
    sequence_parallel_size: int = 1
    tensor_parallel_size: int = 1

    @property
    def total_devices(self) -> int:
        return (
            self.data_parallel_size
            * self.sequence_parallel_size
            * self.tensor_parallel_size
        )


def build_mesh(
    axes: MeshAxes | None = None,
    *,
    tensor_parallel_size: int = 1,
    data_parallel_size: int = 1,
    sequence_parallel_size: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a ``(dp, sp, tp)`` mesh over the available devices.

    The tp axis is placed innermost so neighbouring mesh coordinates map to
    neighbouring chips (``jax.devices()`` enumerates in ICI order on TPU),
    keeping per-layer all-reduces on the fastest links.
    """
    if axes is None:
        axes = MeshAxes(
            data_parallel_size=data_parallel_size,
            sequence_parallel_size=sequence_parallel_size,
            tensor_parallel_size=tensor_parallel_size,
        )
    devices = list(devices if devices is not None else jax.devices())
    need = axes.total_devices
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices "
            f"(dp={axes.data_parallel_size} × sp={axes.sequence_parallel_size}"
            f" × tp={axes.tensor_parallel_size}) but only "
            f"{len(devices)} are visible"
        )
    grid = np.asarray(devices[:need]).reshape(
        axes.data_parallel_size,
        axes.sequence_parallel_size,
        axes.tensor_parallel_size,
    )
    mesh = Mesh(grid, AXIS_NAMES)
    logger.info(
        "built device mesh dp=%d sp=%d tp=%d over %d %s device(s)",
        axes.data_parallel_size,
        axes.sequence_parallel_size,
        axes.tensor_parallel_size,
        need,
        devices[0].platform,
    )
    return mesh


def mesh_from_parallel_config(pcfg, devices=None) -> Mesh | None:
    """Mesh for ONE engine replica's ParallelConfig (always dp=1 here:
    in-process data parallelism lives a level up, in
    ``AsyncLLMEngine.from_config``, which builds one LLMEngine per dp
    rank over a disjoint device slice and passes it down via ``devices``).

    Returns None for the plain single-chip path; fails fast on modes the
    engine does not implement yet, so a flag the CLI accepts can never
    silently run unsharded.  With an explicit ``devices`` list a mesh is
    built even at sp=tp=1 — a 1×1×1 mesh pins every array of that replica
    to its one assigned device, which default placement would not.
    """
    if pcfg.pipeline_parallel_size > 1:
        raise NotImplementedError(
            "this function builds the mesh for a single non-pipelined "
            "replica; LLMEngine routes pipeline_parallel_size > 1 "
            "through engine/pipeline.py (PipelineRunner), which builds "
            "one mesh per stage itself"
        )
    if pcfg.data_parallel_size > 1:
        raise NotImplementedError(
            "LLMEngine is always a single dp rank; construct via "
            "AsyncLLMEngine.from_config for in-process --data-parallel-"
            "size replicas"
        )
    sp = getattr(pcfg, "sequence_parallel_size", 1)
    if pcfg.tensor_parallel_size <= 1 and sp <= 1 and devices is None:
        return None
    return build_mesh(
        tensor_parallel_size=pcfg.tensor_parallel_size,
        sequence_parallel_size=sp,
        devices=devices,
    )


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-host runtime (one controller process per host).

    Wraps ``jax.distributed.initialize``; on TPU pods all arguments are
    discovered from the metadata server, so a bare call suffices.  Must run
    before the first device query.  The reference's analog is vLLM's
    Ray/MP worker launch; here every host runs the same SPMD program and
    XLA handles cross-host collectives over ICI/DCN.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "multi-host initialised: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
