"""Ring attention: causal prefill sharded over the sequence axis.

Long-context scale-out path (SURVEY.md §5 "long-context"): when a prompt
exceeds one chip's HBM (activations + KV), the sequence axis is sharded
over the mesh's ``sp`` axis and K/V chunks rotate around the ring via
``ppermute`` while every device accumulates online-softmax partials for
its local queries.  Peak per-device memory is O(T/n) and the ring rides
the ICI neighbour links; compute overlaps the rotation because XLA
schedules the collective-permute asynchronously.

Causality over chunks: device d owns global positions [d·c, (d+1)·c); a
K/V chunk originating from device s is fully visible when s < d, fully
masked when s > d, and diagonally masked when s == d — so each hop does
full-block work and the mask only materialises on the diagonal hop.

Numerics mirror ops/attention.py:prefill_attention_xla (f32 softmax);
parity is pinned on the virtual CPU mesh in tests/test_ring_attention.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from vllm_tgis_adapter_tpu.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from vllm_tgis_adapter_tpu.parallel.mesh import SP_AXIS, TP_AXIS

NEG_INF = float("-inf")


def _chunk_attention(
    q: jax.Array,  # [C, Hkv, G, Dh] f32 local queries
    k: jax.Array,  # [C, Hkv, Dh] f32 visiting key chunk
    v: jax.Array,  # [C, Hkv, Dh] f32
    scale: float,
    q_pos: jax.Array,  # [C] global positions of local queries
    k_pos: jax.Array,  # [C] global positions of the visiting chunk
    valid_len: jax.Array,
    m: jax.Array,  # [Hkv, G, C, 1] running max
    l: jax.Array,  # [Hkv, G, C, 1] running denom
    acc: jax.Array,  # [Hkv, G, C, Dh] running numerator
    window: int = 0,  # >0: band mask over GLOBAL positions
    slopes: jax.Array | None = None,  # [Hkv, G] f32 ALiBi slopes
):
    s = jnp.einsum("ckgd,skd->kgcs", q, k) * scale  # [Hkv, G, C, C]
    if slopes is not None:
        # HF bloom convention (ops/attention.py prefill_attention_xla):
        # score += slope_h * j with j the GLOBAL key position — the
        # row-constant term cancels in softmax, and global positions
        # keep the bias identical across ring hops
        s = s + (
            slopes[:, :, None, None]
            * k_pos.astype(jnp.float32)[None, None, None, :]
        )
    mask = (k_pos[None, :] <= q_pos[:, None]) & (
        k_pos[None, :] < valid_len
    )  # [C, C]
    if window > 0:
        # band over global positions: query i sees keys (i-window, i];
        # hops entirely below the band contribute nothing (all -inf,
        # alpha carries prior partials through unchanged)
        mask = mask & (
            (q_pos[:, None] - k_pos[None, :]) < window
        )
    s = jnp.where(mask[None, None], s, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, shift) - shift)
    l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc = alpha * acc + jnp.einsum("kgcs,skd->kgcd", p, v)
    return m_new, l, acc


def ring_prefill_attention(
    q: jax.Array,  # [T, H, Dh] sequence-sharded on sp
    k: jax.Array,  # [T, Hkv, Dh]
    v: jax.Array,
    scale: float,
    valid_len: jax.Array,  # scalar int32 (global)
    mesh: Mesh,
    axis: str = SP_AXIS,
    window: int = 0,  # mistral-style sliding window (0 = full causal)
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
) -> jax.Array:
    """Causal attention with the sequence axis sharded over ``axis``.

    All inputs/outputs are global-view arrays; shard_map splits them so
    each device keeps only its T/n chunk resident.  On a joint sp×tp mesh
    the head axis is additionally split over tp, so every device holds a
    (T/sp, H/tp) tile — ring hops move only local-head K/V chunks.
    """
    n = mesh.shape[axis]
    if n == 1:
        from vllm_tgis_adapter_tpu.ops.attention import prefill_attention_xla

        return prefill_attention_xla(q, k, v, scale, valid_len,
                                     window=window,
                                     alibi_slopes=alibi_slopes)
    t, _, head_dim = q.shape
    if t % n:
        raise ValueError(f"sequence {t} not divisible by ring size {n}")
    c = t // n
    tp = dict(mesh.shape).get(TP_AXIS, 1)
    head_axis = TP_AXIS if tp > 1 else None

    def local_fn(q_loc, k_loc, v_loc, vl, slopes_loc):
        # q_loc [C, H/tp, Dh]; k_loc/v_loc [C, Hkv/tp, Dh]; vl [1];
        # slopes_loc [H/tp] (zero-size placeholder when ALiBi is off)
        d = jax.lax.axis_index(axis)
        num_heads = q_loc.shape[1]
        num_kv = k_loc.shape[1]
        g = num_heads // num_kv
        qf = q_loc.reshape(c, num_kv, g, head_dim).astype(jnp.float32)
        q_pos = d * c + jnp.arange(c)
        slopes = (
            slopes_loc.reshape(num_kv, g).astype(jnp.float32)
            if slopes_loc.size else None
        )

        m = jnp.full((num_kv, g, c, 1), NEG_INF, jnp.float32)
        l = jnp.zeros((num_kv, g, c, 1), jnp.float32)
        acc = jnp.zeros((num_kv, g, c, head_dim), jnp.float32)

        k_cur = k_loc.astype(jnp.float32)
        v_cur = v_loc.astype(jnp.float32)
        # ring size is static (mesh shape): unrolled python loop lets XLA
        # pipeline each hop's ppermute under the previous hop's compute
        for i in range(n):
            src = (d - i) % n  # chunk currently visiting this device
            k_pos = src * c + jnp.arange(c)
            m, l, acc = _chunk_attention(
                qf, k_cur, v_cur, scale, q_pos, k_pos, vl[0], m, l, acc,
                window=window, slopes=slopes,
            )
            if i != n - 1:
                perm = [(j, (j + 1) % n) for j in range(n)]
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)

        out = acc / jnp.maximum(l, 1e-30)  # [Hkv, G, C, Dh]
        out = jnp.transpose(out, (2, 0, 1, 3)).reshape(
            c, num_heads, head_dim
        )
        return out.astype(q_loc.dtype)

    seq = P(axis, head_axis, None)
    slopes_in = (
        jnp.zeros((0,), jnp.float32)
        if alibi_slopes is None
        else alibi_slopes.astype(jnp.float32)
    )
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(seq, seq, seq, P(), P(head_axis)),
        out_specs=seq,
        check_vma=False,
    )(q, k, v, jnp.asarray([valid_len], jnp.int32), slopes_in)
