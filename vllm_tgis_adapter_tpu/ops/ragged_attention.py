"""Ragged paged attention: one kernel, one dispatch, zero bucket padding.

The bucketed data path runs THREE attention families per engine step —
solo/packed flash prefill over a padded prompt bucket, chunked prefill
against the paged cache, and a per-batch-width decode ladder
(folded → perhead → xla).  Every family carries its own compile lattice
and its own padding.  This module collapses them into ONE computation
(PAPERS.md: *Ragged Paged Attention — A High-Performance and Flexible
LLM Inference Kernel for TPU*): the engine hands the kernel a FLAT token
stream in which each sequence owns a contiguous span — a whole prompt, a
prefill chunk, or a single decode token — plus per-sequence descriptors,
and every row attends causally to its sequence's paged KV context.  A
mixed prefill+decode batch is one dispatch with no per-prompt bucket
padding; the only pad is the tail of the single flat-length bucket.

Layout contract (shared with ops/attention.py):
* KV cache per layer is head-leading ``[Hkv, num_slots, Dh]`` — a page is
  a contiguous ``(block_size, Dh)`` Mosaic-legal tile;
* the caller scatters this step's K/V into the cache BEFORE attention,
  so prefill rows see their own chunk and decode rows see their token
  through the same paged read path — that unification is what removes
  the separate prefill/decode kernels.

Descriptors (all device arrays; S = padded sequence-descriptor width):
* ``seq_starts [S+1]`` — flat row where sequence s's span begins; spans
  are contiguous and sorted; unused/pad entries hold the padded stream
  length, so a span's membership test is just its two bounds;
* ``pos_base [S]`` — global position of sequence s's first row (chunk
  ``start_pos``; ``num_tokens - 1`` for a decode row);
* ``block_tables [S, max_blocks]`` — page table per sequence;
* ``positions [T]`` — global position per row (redundant with
  pos_base/seq_starts; the XLA path uses it directly, the Pallas kernel
  re-derives it from SMEM scalars to avoid vector gathers).

Pallas kernel: grid ``(kv_head, work_item)`` over a precomputed WORK
SCHEDULE — one item per (query block, sequence, logical page) triple that
actually overlaps, exactly the ragged-friendly formulation the paper's
kernel uses instead of a dense (batch, page) grid.  The schedule rides
scalar prefetch; pages DMA straight out of the paged cache via the
BlockSpec index map (the gather happens in the memory system).  Mixed
engine steps pass a host-built sparse schedule (``build_work_schedule``);
in-jit callers (the fused decode scan) build the dense per-row schedule
in-trace (``dense_work_schedule``).  Numerics: f32 online-softmax
accumulation, masking identical to ``paged_decode_attention_xla`` — the
XLA path below IS that function, so parity is pinned to the same
reference the bucketed kernels are.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_tgis_adapter_tpu.jax_compat import shard_map
from vllm_tgis_adapter_tpu.ops.attention import (
    NEG_INF,
    _pallas_interpret,
    _use_pallas,
    paged_decode_attention_xla,
)

#: work-schedule row layout ([WORK_FIELDS, W] i32): query-block index,
#: sequence id, physical page id (DMA target), logical page index within
#: the sequence, first-item-of-block flag, last-item-of-block flag,
#: live flag (0 = padding/masked item: no compute, accumulators only).
WORK_FIELDS = 7


def _pow2_ceil(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


# ------------------------------------------------------------- schedules


def build_work_schedule(
    spans: list[tuple[int, int, int]],  # per seq: (start_row, n_rows, pos_base)
    block_tables: "np.ndarray",  # [S, max_blocks] int32
    *,
    block_size: int,
    block_q: int,
    t_pad: int,
    w_bucket: int | None = None,
) -> "np.ndarray":
    """Host-side sparse schedule for a mixed ragged batch.

    Emits one work item per (query block, sequence, logical page) triple
    whose page could be causally visible to some row of that sequence in
    that block — the exact page set, so the kernel never DMAs a page no
    row reads.  Windowed layers mask inside the kernel (the schedule is
    shared across layers and some layers may be full-attention, so it
    must cover the full causal span).  Every query block gets at least
    one item (a dead one if the block is all padding) so its output
    block is always initialised and finalised.

    Returns ``[WORK_FIELDS, W]`` int32 with W padded to a power of two
    (``w_bucket`` overrides) — the schedule width is a compile shape.
    """
    nq = t_pad // block_q
    per_block: list[list[tuple[int, int, int, int]]] = [[] for _ in range(nq)]
    for s, (start, n_rows, pos0) in enumerate(spans):
        if n_rows <= 0:
            continue
        lo_block = start // block_q
        hi_block = (start + n_rows - 1) // block_q
        for qb in range(lo_block, hi_block + 1):
            # deepest position any of this sequence's rows in block qb
            # can see: its last row's own position
            row_hi = min(start + n_rows - 1, (qb + 1) * block_q - 1)
            max_pos = pos0 + (row_hi - start)
            for j in range(max_pos // block_size + 1):
                per_block[qb].append((s, int(block_tables[s, j]), j, 1))
    items: list[tuple[int, ...]] = []
    for qb in range(nq):
        blk = per_block[qb] or [(0, 0, 0, 0)]  # dead item: zeros the block
        for i, (s, page, j, live) in enumerate(blk):
            items.append((
                qb, s, page, j,
                1 if i == 0 else 0,
                1 if i == len(blk) - 1 else 0,
                live,
            ))
    w = len(items)
    width = w_bucket or _pow2_ceil(w)
    work = np.zeros((WORK_FIELDS, width), np.int32)
    work[:, :w] = np.asarray(items, np.int32).T
    if width > w:
        # pads keep the final real block's index so the output pipeline
        # never revisits an earlier block; flags all zero = no-ops
        work[0, w:] = items[-1][0]
    return work


def dense_work_schedule(
    pos_base: jax.Array,  # [S] i32: context position per row (= seq)
    block_tables: jax.Array,  # [S, max_blocks] i32
    *,
    block_size: int,
    block_q: int,
    t_pad: int,
) -> jax.Array:
    """In-trace schedule for the fused decode scan, where every span is
    exactly ONE row (``seq_starts = arange(S+1)``): sequence *s* IS flat
    row *s*, so its items live only in query block ``s // block_q`` and
    the schedule is the plain (sequence, logical page) cross product —
    W = S · max_blocks grid steps, nq× fewer than the general
    (q-block, sequence, page) product would need.  Pages past a row's
    context carry ``live=0`` with their DMA index clamped to a live page
    so consecutive identical indices elide the transfer (same trick as
    the decode kernel's ``page_index``).  Descriptor slots past the
    stream (pad sequences, when the caller's S exceeds the row count)
    clamp onto the last query block; their rows sit outside every real
    span, so the kernel masks them and only pad outputs are touched.
    """
    s_count, max_blocks = block_tables.shape
    nq = t_pad // block_q
    w = jnp.arange(s_count * max_blocks, dtype=jnp.int32)
    s = w // max_blocks
    j = w % max_blocks
    qb = jnp.minimum(s // block_q, nq - 1)
    max_pos = jnp.take(pos_base, s)
    live = j * block_size <= max_pos
    j_eff = jnp.minimum(j, jnp.maximum(max_pos, 0) // block_size)
    page = jnp.take_along_axis(
        jnp.take(block_tables, s, axis=0), j_eff[:, None], axis=1
    )[:, 0]
    page = jnp.clip(page, 0, None)
    # first/last flags on the block TRANSITIONS (not modular indexing):
    # the clamp above can hand the last block a ragged item count, and
    # every block's accumulators must init exactly once and finalise on
    # the true final item
    step = qb[1:] != qb[:-1]
    edge = jnp.ones(1, bool)
    first = jnp.concatenate([edge, step]).astype(jnp.int32)
    last = jnp.concatenate([step, edge]).astype(jnp.int32)
    return jnp.stack([
        qb, s, page, j, first, last, live.astype(jnp.int32)
    ])


# ----------------------------------------------------------------- kernel


def _ragged_kernel(
    # scalar prefetch
    work_ref,  # [WORK_FIELDS, W] SMEM work schedule
    starts_ref,  # [S+1] SMEM flat span starts (pads = padded length)
    base_ref,  # [S] SMEM global position of each span's first row
    alibi_ref,  # [H] f32 SMEM slopes; unused unless use_alibi
    # blocks: q_ref [1, G*bq, Dh], k_ref/v_ref [1, block_size, Dh] (the
    # page picked by index_map), then — quantized caches only — ks_ref/
    # vs_ref [1, 1] f32 (the page's dequant scale, same index map), then
    # o_ref [1, G*bq, Dh] and the three f32 scratch accumulators
    # (m [G*bq, 1], l [G*bq, 1], acc [G*bq, Dh])
    q_ref,
    k_ref,
    v_ref,
    *refs,
    scale: float,
    block_size: int,
    block_q: int,
    g: int,
    window: int,
    use_alibi: bool,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = refs
    h = pl.program_id(0)
    w = pl.program_id(1)
    seq = work_ref[1, w]
    page_pos = work_ref[3, w]

    @pl.when(work_ref[4, w] == 1)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(work_ref[6, w] == 1)
    def _item():
        q = q_ref[0].astype(jnp.float32)  # [G*bq, Dh]
        k = k_ref[0].astype(jnp.float32)  # [bs, Dh]
        v = v_ref[0].astype(jnp.float32)
        if quantized:
            # in-register dequant: the whole page tile shares ONE
            # per-(kv head, page) scale (ops/kv_quant.py sidecar),
            # DMA'd as a 1x1 block by the same page index map
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s_mat = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G*bq, bs]
        # rows are (g, i) flattened row-major (chunked-kernel layout):
        # flat token index = qb*bq + row % bq
        row = jax.lax.broadcasted_iota(jnp.int32, s_mat.shape, dimension=0)
        tok = work_ref[0, w] * block_q + row % block_q
        # the item already names its sequence, and spans are contiguous
        # and sorted — membership and global position are two SMEM
        # scalar reads of the span bounds, not a scan over every
        # descriptor slot (no vector gathers either way; rows outside
        # the span mask out, so their garbage pos_row never matters)
        start = starts_ref[seq]
        pos_row = base_ref[seq] + tok - start
        col = jax.lax.broadcasted_iota(jnp.int32, s_mat.shape, dimension=1)
        k_pos = page_pos * block_size + col
        keep = (
            (tok >= start)
            & (tok < starts_ref[seq + 1])
            & (k_pos <= pos_row)
        )
        if window > 0:
            keep &= pos_row - k_pos < window
        if use_alibi:
            # query head = h·G + (row // bq); 2-D selects, no 1-D gathers
            slopes = jnp.full(s_mat.shape, alibi_ref[h * g], jnp.float32)
            for gi in range(1, g):
                slopes = jnp.where(
                    row // block_q == gi, alibi_ref[h * g + gi], slopes
                )
            s_mat = s_mat + slopes * k_pos.astype(jnp.float32)
        s_mat = jnp.where(keep, s_mat, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s_mat, axis=-1, keepdims=True))
        # fully masked rows keep m == -inf; pin the shift finite so exp
        # stays NaN-free (house convention, see _prefill_kernel)
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s_mat - shift)
        alpha = jnp.exp(
            jnp.where(jnp.isfinite(m_prev), m_prev, shift) - shift
        )
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(work_ref[5, w] == 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _ragged_attention_pallas(
    q: jax.Array,  # [T, H, Dh] flat mixed stream
    k_cache: jax.Array,  # [Hkv, num_slots, Dh]
    v_cache: jax.Array,
    seq_starts: jax.Array,  # [S+1]
    pos_base: jax.Array,  # [S]
    work: jax.Array,  # [WORK_FIELDS, W]
    block_size: int,
    scale: float,
    *,
    block_q: int,
    window: int,
    alibi_slopes: jax.Array | None,
    interpret: bool,
    kv_scales: tuple | None = None,  # ([Hkv, pages] f32 x2) quantized
) -> jax.Array:
    t, num_heads, head_dim = q.shape
    num_kv = k_cache.shape[0]
    g = num_heads // num_kv
    block_q = min(block_q, _pow2_ceil(t))
    nq = pl.cdiv(t, block_q)
    t_pad = nq * block_q

    # [Hkv, nq·G·bq, Dh] with each q block laid out (G, bq) row-major —
    # the chunked-prefill kernel's layout: one page DMA serves the whole
    # GQA group of the block
    qp = jnp.pad(q, ((0, t_pad - t), (0, 0), (0, 0)))
    qh = jnp.transpose(
        qp.reshape(nq, block_q, num_kv, g, head_dim), (2, 0, 3, 1, 4)
    ).reshape(num_kv, nq * g * block_q, head_dim)

    slopes = (
        jnp.zeros(num_heads, jnp.float32)
        if alibi_slopes is None
        else alibi_slopes.astype(jnp.float32)
    )
    num_work = work.shape[1]
    quantized = kv_scales is not None
    in_specs = [
        pl.BlockSpec(
            (1, g * block_q, head_dim),
            lambda h, w, wk, st, bs_, al: (h, wk[0, w], 0),
        ),
        pl.BlockSpec(
            (1, block_size, head_dim),
            lambda h, w, wk, st, bs_, al: (h, wk[2, w], 0),
        ),
        pl.BlockSpec(
            (1, block_size, head_dim),
            lambda h, w, wk, st, bs_, al: (h, wk[2, w], 0),
        ),
    ]
    operands = [qh, k_cache, v_cache]
    if quantized:
        # one (kv head, page) scale scalar per cache, picked by the same
        # physical-page index the K/V tiles DMA with — the in-register
        # dequant's only extra traffic is two 4-byte blocks per item
        scale_spec = pl.BlockSpec(
            (1, 1), lambda h, w, wk, st, bs_, al: (h, wk[2, w])
        )
        in_specs += [scale_spec, scale_spec]
        operands += [
            kv_scales[0].astype(jnp.float32),
            kv_scales[1].astype(jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(num_kv, num_work),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, g * block_q, head_dim),
            lambda h, w, wk, st, bs_, al: (h, wk[0, w], 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((g * block_q, 1), jnp.float32),
            pltpu.VMEM((g * block_q, 1), jnp.float32),
            pltpu.VMEM((g * block_q, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel, scale=scale, block_size=block_size,
            block_q=block_q, g=g, window=window,
            use_alibi=alibi_slopes is not None, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_kv, nq * g * block_q, head_dim), q.dtype
        ),
        interpret=interpret,
    )(work, seq_starts.astype(jnp.int32), pos_base.astype(jnp.int32),
      slopes, *operands)
    return jnp.transpose(
        out.reshape(num_kv, nq, g, block_q, head_dim), (1, 3, 0, 2, 4)
    ).reshape(t_pad, num_heads, head_dim)[:t]


# --------------------------------------------------------------- dispatch


def ragged_attention_xla(
    q: jax.Array,  # [T, H, Dh] flat mixed stream
    k_cache: jax.Array,  # [Hkv, num_slots, Dh]
    v_cache: jax.Array,
    positions: jax.Array,  # [T] global position per row
    seq_starts: jax.Array,  # [S+1] flat span starts (pads = T)
    total_tokens: jax.Array,  # scalar: real rows in the stream
    block_tables: jax.Array,  # [S, max_blocks]
    block_size: int,
    scale: float,
    *,
    window: int = 0,
    alibi_slopes: jax.Array | None = None,
    kv_scales: tuple | None = None,
) -> jax.Array:
    """XLA reference: every ragged row IS a decode row with context
    length ``position + 1`` against its sequence's page table — the
    formulation the bucketed chunked-prefill fallback already pins its
    numerics to, generalised to a mixed multi-sequence stream."""
    t = q.shape[0]
    num_seqs = block_tables.shape[0]
    rows = jnp.arange(t, dtype=jnp.int32)
    seq = jnp.sum(
        rows[:, None] >= seq_starts[None, :num_seqs].astype(jnp.int32),
        axis=1,
    ) - 1
    seq = jnp.clip(seq, 0, num_seqs - 1)
    tables = jnp.take(block_tables, seq, axis=0)  # [T, max_blocks]
    ctx = jnp.where(rows < total_tokens, positions.astype(jnp.int32) + 1, 1)
    return paged_decode_attention_xla(
        q, k_cache, v_cache, tables, ctx, block_size, scale,
        window=window, alibi_slopes=alibi_slopes, kv_scales=kv_scales,
    )


def ragged_paged_attention(
    q: jax.Array,  # [T, H, Dh] flat mixed stream
    k_cache: jax.Array,  # [Hkv, num_slots, Dh] head-leading
    v_cache: jax.Array,
    positions: jax.Array,  # [T]
    seq_starts: jax.Array,  # [S+1]
    pos_base: jax.Array,  # [S]
    total_tokens: jax.Array,  # scalar
    block_tables: jax.Array,  # [S, max_blocks]
    block_size: int,
    scale: float,
    *,
    work: jax.Array | None = None,  # [WORK_FIELDS, W] or None
    mesh=None,
    window: int = 0,
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
    block_q: int = 128,
    kv_scales: tuple | None = None,  # ([Hkv, pages] f32 x2) quantized KV
) -> jax.Array:
    """One causal paged-attention dispatch over a mixed ragged stream.

    The caller must have scattered this step's K/V into the cache first.
    TPU runs the Pallas work-schedule kernel (``work`` from
    ``build_work_schedule``; built densely in-trace when None, the fused
    decode-scan case); elsewhere the XLA reference runs and ``work`` is
    ignored entirely — it never becomes an operand, so schedule-width
    shape variety cannot retrace the CPU path.

    ``kv_scales`` marks the caches as quantized pages (ops/kv_quant.py):
    the Pallas kernel dequantizes each page tile in-register against its
    one per-(kv head, page) scale, the XLA path right after its gather.

    Under a TP mesh the kernel runs inside shard_map over the head axis,
    cache head-sharded — same contract as the bucketed kernels.
    """
    if _use_pallas():
        if work is None:
            # dense in-trace schedule (the fused decode scan; requires
            # single-row spans, seq_starts = arange): small q blocks —
            # every span is one row, so a wide block would only
            # multiply masked work items per (block, seq) pair.
            # t_pad must equal the kernel's cdiv padding: a wider pad
            # (e.g. pow2) emits query-block indices past the kernel's
            # output grid, and their first/last flags would re-init and
            # finalise a clamped real block with zeros
            block_q = min(block_q, 8, _pow2_ceil(q.shape[0]))
            work = dense_work_schedule(
                pos_base, block_tables,
                block_size=block_size, block_q=block_q,
                t_pad=-(-q.shape[0] // block_q) * block_q,
            )
        kernel = functools.partial(
            _ragged_attention_pallas,
            block_size=block_size,
            scale=scale,
            block_q=block_q,
            window=window,
            interpret=_pallas_interpret(),
        )
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            heads = P(None, "tp", None)
            cache = P("tp", None, None)
            operands = [q, k_cache, v_cache, seq_starts, pos_base, work]
            specs = [heads, cache, cache, P(), P(), P()]
            n_scales = 0
            if kv_scales is not None:
                # scale sidecars shard with the kv-head axis like the
                # caches they dequantize
                operands.extend(kv_scales)
                specs.extend([P("tp", None), P("tp", None)])
                n_scales = 2
            if alibi_slopes is not None:
                operands.append(alibi_slopes)
                specs.append(P("tp"))

            def wrapped(q, kc, vc, st, pb, wk, *rest):
                scales = tuple(rest[:n_scales]) if n_scales else None
                rest = rest[n_scales:]
                return kernel(q, kc, vc, st, pb, wk,
                              alibi_slopes=rest[0] if rest else None,
                              kv_scales=scales)

            return shard_map(
                wrapped, mesh=mesh, in_specs=tuple(specs),
                out_specs=heads, check_vma=False,
            )(*operands)
        return kernel(q, k_cache, v_cache, seq_starts, pos_base, work,
                      alibi_slopes=alibi_slopes, kv_scales=kv_scales)
    return ragged_attention_xla(
        q, k_cache, v_cache, positions, seq_starts, total_tokens,
        block_tables, block_size, scale,
        window=window, alibi_slopes=alibi_slopes, kv_scales=kv_scales,
    )
