"""Pallas TPU kernels: paged decode attention + flash causal prefill.

Native tier of the attention stack (SURVEY.md §2.2: the reference serves
through vLLM's CUDA paged-attention kernels; these are the TPU-first
equivalents).  Both kernels stream K/V through VMEM with an online-softmax
accumulator, so HBM traffic is one read of the live context in cache
dtype — unlike the XLA fallbacks in ops/attention.py, which materialise
float32 ``[B, S, Hkv, Dh]`` gathers (decode) or ``[Hkv, g, T, T]`` score
tensors (prefill).

Decode kernel layout: grid ``(batch, kv_head, page)``; the page axis is
innermost so the per-(seq, head) accumulator lives in VMEM scratch across
page steps.  Block tables are scalar-prefetched and drive the K/V page
BlockSpec index maps directly — the pipeline DMAs exactly the pages the
block table names, i.e. the gather happens in the memory system, not in
registers.

The KV cache is **head-leading**: ``[Hkv, num_slots, Dh]`` per layer, so a
page block is ``(1, block_size, Dh)`` — its trailing two dims are
(sublane, lane) = (block_size, head_dim), a legal Mosaic tile for
``block_size`` a multiple of the dtype's sublane quantum (8 for f32, 16
for bf16) and any ``Dh`` (the block spans the full array dim).  A
slot-leading layout ``[num_slots, Hkv, Dh]`` would force the illegal
``(block_size, 1, Dh)`` block whose middle dim can't tile the head axis —
Mosaic rejects it for every real config, which is exactly why the layout
is a kernel-design decision, not a storage detail.

Numerics: f32 accumulation (MXU-friendly: bf16 in, f32 out), identical
masking semantics to the XLA reference; parity is pinned by
tests/test_pallas_attention.py in interpreter mode on CPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


# --------------------------------------------------------------------- decode


def _chunk_kernel(
    # scalar prefetch
    block_table_ref,  # [max_blocks] SMEM — this sequence's page table
    meta_ref,  # [2] SMEM: (start_pos, valid_len)
    alibi_ref,  # [H] f32 SMEM slopes; unused unless use_alibi
    # blocks
    q_ref,  # [1, G*bq, Dh] VMEM (query block iq of kv head h)
    k_ref,  # [1, block_size, Dh] VMEM — page picked by index_map
    v_ref,  # [1, block_size, Dh]
    o_ref,  # [1, G*bq, Dh]
    # scratch
    m_ref,  # [G*bq, 1] f32
    l_ref,  # [G*bq, 1] f32
    acc_ref,  # [G*bq, Dh] f32
    *,
    scale: float,
    block_size: int,
    block_q: int,
    g: int,
    window: int,
    use_alibi: bool,
):
    h = pl.program_id(0)
    iq = pl.program_id(1)
    j = pl.program_id(2)
    last = pl.num_programs(2) - 1
    start = meta_ref[0]
    valid = meta_ref[1]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # the page is live when it starts at or before the LAST query of this
    # block (causality), holds real context, and (with a sliding window)
    # reaches the FIRST query's band
    q_hi = start + iq * block_q + block_q - 1
    live = (j * block_size <= q_hi) & (j * block_size < start + valid)
    if window > 0:
        band_lo = start + iq * block_q - window + 1
        live &= (j + 1) * block_size > band_lo

    @pl.when(live)
    def _page():
        q = q_ref[0].astype(jnp.float32)  # [G*bq, Dh]
        k = k_ref[0].astype(jnp.float32)  # [bs, Dh]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G*bq, bs]

        # rows are (g, i) flattened row-major: query index i = row % bq
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=0)
        q_pos = start + iq * block_q + row % block_q
        k_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        )
        if use_alibi:
            # rows are (g, i) flattened row-major: g = row // block_q;
            # query head = h·G + g. Built with 2-D selects — a 1-D
            # [G·bq] repeat+reshape is a shape cast Mosaic can't lower.
            slopes = jnp.full(s.shape, alibi_ref[h * g], jnp.float32)
            for gi in range(1, g):
                slopes = jnp.where(
                    row // block_q == gi, alibi_ref[h * g + gi], slopes
                )
            s = s + slopes * k_pos.astype(jnp.float32)
        mask = (k_pos <= q_pos) & (k_pos < start + valid)
        if window > 0:
            mask &= q_pos - k_pos < window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # fully masked rows (padding queries) keep m == -inf; pin the
        # shift to a finite value so exp() stays NaN-free
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev, shift) - shift)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(j == last)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block_size", "scale", "block_q", "window", "interpret"
    ),
)
def chunked_prefill_attention(
    q: jax.Array,  # [T, H, Dh] one chunk's queries (padded bucket)
    k_cache: jax.Array,  # [Hkv, num_slots, Dh] head-leading paged cache
    v_cache: jax.Array,
    block_table: jax.Array,  # [max_blocks] int32, this sequence's pages
    start_pos: jax.Array,  # scalar: tokens already in cache before chunk
    valid_len: jax.Array,  # scalar: real tokens in this chunk
    block_size: int,
    scale: float,
    *,
    block_q: int = 128,
    window: int = 0,
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
    interpret: bool = False,
) -> jax.Array:
    """Causal attention of one prompt chunk against its paged context.

    The chunk's own K/V must already be scattered into the cache.  Every
    page is DMA'd ONCE per (kv head, query block) and its read is shared
    by all ``G × block_q`` query rows — versus the decode-kernel
    formulation of this computation, which re-reads the page for every
    individual query token (T× the HBM traffic).  Causality is the
    logical page index j: the j-th table entry covers sequence positions
    [j·bs, (j+1)·bs), so the mask needs no gather.
    """
    t, num_heads, head_dim = q.shape
    num_kv = k_cache.shape[0]
    g = num_heads // num_kv
    max_blocks = block_table.shape[0]
    block_q = min(block_q, t)
    nq = pl.cdiv(t, block_q)
    t_pad = nq * block_q

    # [Hkv, nq·G·bq, Dh] with each q block laid out (G, bq) row-major:
    # kv head outermost so one page read serves the head's whole GQA
    # group × the query block
    qp = jnp.pad(q, ((0, t_pad - t), (0, 0), (0, 0)))
    qh = jnp.transpose(
        qp.reshape(nq, block_q, num_kv, g, head_dim), (2, 0, 3, 1, 4)
    ).reshape(num_kv, nq * g * block_q, head_dim)

    safe_table = jnp.clip(block_table, 0, k_cache.shape[1] // block_size - 1)

    def page_index(h, iq, j, bt, meta):
        # clamp steps past this q block's causal horizon (and, windowed,
        # below its band) to a live page: consecutive identical indices
        # elide the DMA entirely
        last_needed = jnp.minimum(
            (meta[0] + iq * block_q + block_q - 1) // block_size,
            jnp.maximum(meta[0] + meta[1] - 1, 0) // block_size,
        )
        j_eff = jnp.minimum(j, last_needed)
        if window > 0:
            first_needed = jnp.maximum(
                meta[0] + iq * block_q - window + 1, 0
            ) // block_size
            j_eff = jnp.maximum(j_eff, first_needed)
        return bt[jnp.clip(j_eff, 0, None)]

    slopes = (
        jnp.zeros(num_heads, jnp.float32)
        if alibi_slopes is None
        else alibi_slopes.astype(jnp.float32)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(num_kv, nq, max_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, g * block_q, head_dim),
                lambda h, iq, j, bt, meta, al: (h, iq, 0),
            ),
            pl.BlockSpec(
                (1, block_size, head_dim),
                lambda h, iq, j, bt, meta, al: (
                    h, page_index(h, iq, j, bt, meta), 0
                ),
            ),
            pl.BlockSpec(
                (1, block_size, head_dim),
                lambda h, iq, j, bt, meta, al: (
                    h, page_index(h, iq, j, bt, meta), 0
                ),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, g * block_q, head_dim),
            lambda h, iq, j, bt, meta, al: (h, iq, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((g * block_q, 1), jnp.float32),
            pltpu.VMEM((g * block_q, 1), jnp.float32),
            pltpu.VMEM((g * block_q, head_dim), jnp.float32),
        ],
    )
    meta = jnp.stack([
        jnp.asarray(start_pos, jnp.int32), jnp.asarray(valid_len, jnp.int32)
    ])
    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel, scale=scale, block_size=block_size,
            block_q=block_q, g=g, window=window,
            use_alibi=alibi_slopes is not None,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (num_kv, nq * g * block_q, head_dim), q.dtype
        ),
        interpret=interpret,
    )(safe_table, meta, slopes, qh, k_cache, v_cache)
    return jnp.transpose(
        out.reshape(num_kv, nq, g, block_q, head_dim), (1, 3, 0, 2, 4)
    ).reshape(t_pad, num_heads, head_dim)[:t]


# -------------------------------------------------------------------- prefill


def _prefill_kernel(
    valid_len_ref,  # [1] SMEM scalar prefetch
    alibi_ref,  # [H] f32 SMEM slopes; unused unless use_alibi
    seg_ref,  # [max_segs] i32 SMEM packed-segment starts; unused unless
    #           use_segs (then entry 0 is 0, unused entries pad with T)
    q_ref,  # [1, bq, Dh]
    k_ref,  # [1, bk, Dh] (kv head h, key block j)
    v_ref,  # [1, bk, Dh]
    o_ref,  # [1, bq, Dh]
    m_ref,  # [bq, 1]
    l_ref,  # [bq, 1]
    acc_ref,  # [bq, Dh]
    *,
    scale: float,
    block_q: int,
    block_k: int,
    window: int,
    use_alibi: bool,
    use_segs: bool,
    max_segs: int,
):
    h = pl.program_id(0)  # query head
    i = pl.program_id(1)  # query block
    j = pl.program_id(2)  # key block
    last = pl.num_programs(2) - 1
    valid = valid_len_ref[0]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: skip key blocks fully beyond this query block; valid_len is
    # scalar-prefetched, so blocks entirely in the padding region (every
    # score masked anyway) are skipped for free too.  With a sliding
    # window, blocks entirely below the query block's band skip as well.
    live = (j * block_k <= i * block_q + block_q - 1) & (j * block_k < valid)
    if window > 0:
        live &= (j + 1) * block_k > i * block_q - window + 1
    if use_segs:
        # packed prefill: skip key blocks that end before this query
        # block's first segment begins — with the causal skip above this
        # prunes whole-block work down to ~sum(len_i^2) over segments.
        # seg(p) = number of segment starts <= p (scalar SMEM reads).
        row_lo = i * block_q
        col_hi = j * block_k + block_k - 1
        seg_row_lo = jnp.int32(0)
        seg_col_hi = jnp.int32(0)
        for b in range(max_segs):
            seg_row_lo += (row_lo >= seg_ref[b]).astype(jnp.int32)
            seg_col_hi += (col_hi >= seg_ref[b]).astype(jnp.int32)
        live &= seg_col_hi >= seg_row_lo

    @pl.when(live)
    def _block():
        q = q_ref[0].astype(jnp.float32)  # [bq, Dh]
        k = k_ref[0].astype(jnp.float32)  # [bk, Dh]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        rows = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=0
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        )
        if use_alibi:
            s = s + alibi_ref[h] * cols.astype(jnp.float32)
        keep = (cols <= rows) & (cols < valid)
        if window > 0:
            keep &= rows - cols < window
        if use_segs:
            # block-diagonal mask: query and key must share a segment
            seg_q = jnp.zeros(rows.shape, jnp.int32)
            seg_k = jnp.zeros(cols.shape, jnp.int32)
            for b in range(max_segs):
                seg_q += (rows >= seg_ref[b]).astype(jnp.int32)
                seg_k += (cols >= seg_ref[b]).astype(jnp.int32)
            keep &= seg_q == seg_k
        s = jnp.where(keep, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # fully masked rows keep m == -inf; exp(-inf - -inf) is nan — pin
        # the shift to a finite value for those rows
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev, shift) - shift)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = alpha * acc_ref[...] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(j == last)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_q", "block_k", "window", "interpret"),
)
def prefill_attention(
    q: jax.Array,  # [T, H, Dh]
    k: jax.Array,  # [T, Hkv, Dh]
    v: jax.Array,
    scale: float,
    valid_len: jax.Array,  # scalar int32
    *,
    block_q: int = 128,
    block_k: int = 128,
    window: int = 0,  # >0: band mask, rows - cols < window
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
    seg_starts: jax.Array | None = None,  # [max_segs] i32 packed starts
    interpret: bool = False,
) -> jax.Array:
    """Flash causal self-attention over one padded prompt bucket.

    GQA is handled by repeating K/V heads logically: the grid runs over
    *query* heads and the K/V BlockSpec maps query head → kv head, so no
    repeated K/V materialisation in HBM.

    ``seg_starts`` turns the mask block-diagonal for packed prefill (see
    ops/attention.py prefill_attention): k prompts concatenated on the
    token axis, each attending only within its own segment.  The starts
    ride scalar prefetch (SMEM) like the block tables do elsewhere, so
    the mask and the block-skip test are scalar reads, not HBM gathers.
    """
    t, num_heads, head_dim = q.shape
    num_kv = k.shape[1]
    g = num_heads // num_kv
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    nq = pl.cdiv(t, block_q)
    nk = pl.cdiv(t, block_k)

    qh = jnp.swapaxes(q, 0, 1)  # [H, T, Dh]
    kh = jnp.swapaxes(k, 0, 1)  # [Hkv, T, Dh]
    vh = jnp.swapaxes(v, 0, 1)

    slopes = (
        jnp.zeros(num_heads, jnp.float32)
        if alibi_slopes is None
        else alibi_slopes.astype(jnp.float32)
    )
    use_segs = seg_starts is not None
    segs = (
        jnp.zeros(1, jnp.int32)
        if seg_starts is None
        else seg_starts.astype(jnp.int32)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(num_heads, nq, nk),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, head_dim),
                lambda h, i, j, vl, al, sg: (h, i, 0),
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda h, i, j, vl, al, sg: (h // g, j, 0),
            ),
            pl.BlockSpec(
                (1, block_k, head_dim),
                lambda h, i, j, vl, al, sg: (h // g, j, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, head_dim),
            lambda h, i, j, vl, al, sg: (h, i, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _prefill_kernel, scale=scale, block_q=block_q,
            block_k=block_k, window=window,
            use_alibi=alibi_slopes is not None,
            use_segs=use_segs, max_segs=int(segs.shape[0]),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_heads, t, head_dim), q.dtype),
        interpret=interpret,
    )(jnp.asarray([valid_len], jnp.int32), slopes, segs, qh, kh, vh)
    return jnp.swapaxes(out, 0, 1)
