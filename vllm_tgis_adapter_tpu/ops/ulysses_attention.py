"""Ulysses (DeepSpeed-style) sequence parallelism for prefill attention.

The alternative long-context scale-out to ring attention (SURVEY.md §2.4):
instead of rotating K/V chunks around the sp ring, two ``all_to_all``
collectives re-partition the activations so each device holds the FULL
sequence for a 1/sp slice of the heads, computes ordinary causal
attention locally, and swaps back:

    [T/sp, H/tp, Dh]  --all_to_all-->  [T, H/(tp·sp), Dh]
         (sequence-sharded)                (head-sharded)

Trade-off vs ring: two bulk all-to-alls (latency-bound, one shot) versus
sp-1 ppermute hops (bandwidth pipelined under compute); Ulysses keeps the
attention inner loop IDENTICAL to the single-device kernel — on TPU the
flash Pallas kernel runs unchanged on the gathered slice, where the ring
must re-implement online softmax across hops.  Requires sp to divide the
per-tp-shard head counts (validated at engine boot, engine/runner.py).

Numerics are pinned against ops/attention.py:prefill_attention_xla on the
virtual CPU mesh in tests/test_ulysses.py.
"""

from __future__ import annotations

import jax
from vllm_tgis_adapter_tpu.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from vllm_tgis_adapter_tpu.parallel.mesh import SP_AXIS, TP_AXIS


def ulysses_prefill_attention(
    q: jax.Array,  # [T, H, Dh] sequence-sharded on sp (global view)
    k: jax.Array,  # [T, Hkv, Dh]
    v: jax.Array,
    scale: float,
    valid_len: jax.Array,  # scalar int32 (global)
    mesh: Mesh,
    axis: str = SP_AXIS,
    window: int = 0,  # mistral-style sliding window (0 = full causal)
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
) -> jax.Array:
    """Causal prefill attention with the sequence axis sharded over
    ``axis``, computed via head/sequence all-to-all re-partitioning.

    All inputs/outputs are global-view arrays; shard_map splits them so
    each device keeps a (T/sp, H/tp) tile at rest and a (T, H/(tp·sp))
    tile during attention.
    """
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.ops import attention as attn_ops

    n = mesh.shape[axis]
    if n == 1:
        return attn_ops.prefill_attention_xla(
            q, k, v, scale, valid_len, window=window,
            alibi_slopes=alibi_slopes,
        )
    t = q.shape[0]
    if t % n:
        raise ValueError(f"sequence {t} not divisible by sp size {n}")
    tp = dict(mesh.shape).get(TP_AXIS, 1)
    head_axis = TP_AXIS if tp > 1 else None

    def local_fn(q_loc, k_loc, v_loc, vl, slopes_loc):
        # [T/sp, H/tp, Dh] → [T, H/(tp·sp), Dh]
        if q_loc.shape[1] % n or k_loc.shape[1] % n:
            raise ValueError(
                f"ulysses needs sp={n} to divide the local head counts "
                f"(q {q_loc.shape[1]}, kv {k_loc.shape[1]})"
            )
        qt = jax.lax.all_to_all(
            q_loc, axis, split_axis=1, concat_axis=0, tiled=True
        )
        kt = jax.lax.all_to_all(
            k_loc, axis, split_axis=1, concat_axis=0, tiled=True
        )
        vt = jax.lax.all_to_all(
            v_loc, axis, split_axis=1, concat_axis=0, tiled=True
        )
        # the head all_to_all keeps chunk j of the local head slice on
        # sp-rank j — slice the slopes the same way so bias follows head
        slopes = None
        if slopes_loc.size:
            j = jax.lax.axis_index(axis)
            slopes = jax.lax.dynamic_slice_in_dim(
                slopes_loc, j * (slopes_loc.shape[0] // n),
                slopes_loc.shape[0] // n,
            )
        if attn_ops._use_pallas():
            from vllm_tgis_adapter_tpu.ops import pallas_attention

            out = pallas_attention.prefill_attention(
                qt, kt, vt, scale, jnp.asarray(vl[0], jnp.int32),
                window=window,
                alibi_slopes=slopes,
                interpret=attn_ops._pallas_interpret(),
            )
        else:
            out = attn_ops.prefill_attention_xla(
                qt, kt, vt, scale, vl[0], window=window,
                alibi_slopes=slopes,
            )
        # [T, H/(tp·sp), Dh] → [T/sp, H/tp, Dh]
        return jax.lax.all_to_all(
            out, axis, split_axis=0, concat_axis=1, tiled=True
        )

    seq = P(axis, head_axis, None)
    slopes_in = (
        jnp.zeros((0,), jnp.float32)
        if alibi_slopes is None
        else alibi_slopes.astype(jnp.float32)
    )
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(seq, seq, seq, P(), P(head_axis)),
        out_specs=seq,
        check_vma=False,
    )(q, k, v, jax.numpy.asarray([valid_len], jax.numpy.int32), slopes_in)
