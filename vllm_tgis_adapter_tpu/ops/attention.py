"""Attention over the paged KV cache.

TPU-native replacement for the paged-attention CUDA kernels the reference
stack executes inside vLLM (SURVEY.md §2.2/§2.3).  This module holds the
XLA-composed implementations: dense causal prefill attention and the
gather-based paged decode formulation.  They are correct on every backend
(CPU tests included) and serve as the numerical reference for the Pallas
TPU kernels in ``pallas_attention.py`` / ``ragged_attention.py``, which
are swapped in at engine boot when running on real TPU hardware.  Decode
itself serves through the unified RAGGED kernel (ops/ragged_attention.py)
— the bucketed folded/perhead decode variant ladder is retired
(docs/ATTENTION.md); ``paged_decode_attention_xla`` below remains as the
shared numerical reference and CPU path.

Layout choices (TPU-first):
* KV cache is one array per K/V of shape ``[num_layers, kv_heads, num_slots,
  head_dim]`` where ``num_slots = num_blocks * block_size`` — head-leading
  so a KV page is a contiguous ``(block_size, head_dim)`` tile, the layout
  Mosaic can DMA as a legal (sublane, lane) block (see
  pallas_attention.py's module docstring); the flat slot dimension keeps
  page writes as scatters and page reads as gathers with plain integer
  indices (no data-dependent shapes, jit-stable).
* softmax runs in float32 regardless of cache dtype (MXU-friendly bf16 in,
  f32 accumulate).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from vllm_tgis_adapter_tpu.jax_compat import shard_map

NEG_INF = float("-inf")

# ATTENTION_BACKEND=pallas|xla|auto (auto: Pallas kernels on TPU, XLA
# fallbacks elsewhere; pallas on a non-TPU backend runs the kernels in
# interpreter mode — slow, tests only)
_BACKEND_ENV = "ATTENTION_BACKEND"

# pallas_call is an opaque custom call the GSPMD partitioner cannot split,
# so under a TP mesh the kernels are wrapped in shard_map over the
# head-sharded axis.  The mesh travels explicitly on the call path
# (model -> dispatch), never via process state: two engines with
# different meshes in one process must not affect each other's retraces.


def _use_pallas() -> bool:
    mode = os.environ.get(_BACKEND_ENV, "auto")
    if mode == "xla":
        return False
    if mode == "pallas":
        return True
    return jax.default_backend() == "tpu"


def _pallas_interpret() -> bool:
    return jax.default_backend() != "tpu"


def write_kv(
    k_cache: jax.Array,  # [Hkv, num_slots, Dh] head-leading
    v_cache: jax.Array,
    k: jax.Array,  # [T, Hkv, Dh]
    v: jax.Array,
    slot_mapping: jax.Array,  # [T] int32 flat slot per token; -1 = drop
) -> tuple[jax.Array, jax.Array]:
    """Scatter new K/V rows into their assigned cache slots.

    Padding tokens carry slot -1; JAX's scatter mode='drop' only discards
    out-of-bounds *positive* indices (negatives wrap), so negatives are
    remapped to num_slots first and then dropped.  A single advanced index
    keeps the indexed dim in place — ``cache[:, safe]`` is ``[Hkv, T, Dh]``
    — so ``k``/``v`` are swapped to head-leading before the scatter.
    """
    k = k.astype(k_cache.dtype)
    v = v.astype(v_cache.dtype)
    safe = jnp.where(slot_mapping < 0, k_cache.shape[1], slot_mapping)
    k_cache = k_cache.at[:, safe].set(jnp.swapaxes(k, 0, 1), mode="drop")
    v_cache = v_cache.at[:, safe].set(jnp.swapaxes(v, 0, 1), mode="drop")
    return k_cache, v_cache


def prefill_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    scale: float,
    valid_len: jax.Array | None = None,
    mesh=None,
    window: int = 0,
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
    seg_starts: jax.Array | None = None,  # [max_segs] i32 packed-prefill
    sp_mode: str = "ring",  # "ring" | "ulysses" sequence-parallel style
) -> jax.Array:
    """Dispatch: flash Pallas kernel on TPU, XLA fallback elsewhere.

    Under a TP mesh the kernel runs inside shard_map over the head axis
    (each shard attends with its local query/kv heads; GQA grouping is
    preserved because tp divides both H and Hkv, parallel/sharding.py).
    Under an sp mesh axis > 1 the sequence axis is sharded instead and
    K/V chunks rotate around the ring (ops/ring_attention.py) — the
    long-context path.

    ``seg_starts`` enables packed (batched) prefill: several prompts are
    concatenated along the token axis and ``seg_starts[b]`` is the flat
    start index of segment b (entry 0 is 0; unused entries pad with T).
    Queries then attend only within their own segment (block-diagonal
    causal mask).  The scheduler only packs on the plain causal path, so
    seg_starts never combines with window/ALiBi/sp.
    """
    if seg_starts is not None and (
        window > 0
        or alibi_slopes is not None
        or (mesh is not None and dict(mesh.shape).get("sp", 1) > 1)
    ):
        raise NotImplementedError(
            "packed prefill (seg_starts) composes only with plain causal "
            "attention; the block-diagonal mask survives as ops-level "
            "machinery only — the serving planner is ragged "
            "(docs/ATTENTION.md)"
        )
    if mesh is not None and dict(mesh.shape).get("sp", 1) > 1:
        # window/ALiBi ride through both sp styles: the ring carries the
        # band mask / position bias in GLOBAL coordinates across hops
        # (ops/ring_attention.py _chunk_attention), ulysses head-slices
        # the slopes to follow its all-to-all repartition
        vl = (
            jnp.asarray(q.shape[0], jnp.int32)
            if valid_len is None
            else valid_len
        )
        if sp_mode == "ulysses":
            from vllm_tgis_adapter_tpu.ops.ulysses_attention import (
                ulysses_prefill_attention,
            )

            return ulysses_prefill_attention(
                q, k, v, scale, vl, mesh, window=window,
                alibi_slopes=alibi_slopes,
            )
        from vllm_tgis_adapter_tpu.ops.ring_attention import (
            ring_prefill_attention,
        )

        return ring_prefill_attention(
            q, k, v, scale, vl, mesh, window=window,
            alibi_slopes=alibi_slopes,
        )
    if _use_pallas():
        from vllm_tgis_adapter_tpu.ops import pallas_attention

        vl = (
            jnp.asarray(q.shape[0], jnp.int32)
            if valid_len is None
            else valid_len
        )
        kernel = functools.partial(
            pallas_attention.prefill_attention,
            scale=scale,
            window=window,
            interpret=_pallas_interpret(),
        )
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            heads = P(None, "tp", None)
            # slopes (when present) shard with the query heads: each tp
            # shard's kernel sees exactly its local heads' slopes
            operands = [q, k, v, vl]
            specs = [heads, heads, heads, P()]
            if alibi_slopes is not None:
                operands.append(("alibi", alibi_slopes, P("tp")))
            if seg_starts is not None:
                operands.append(("segs", seg_starts, P()))
            tagged = [op for op in operands if isinstance(op, tuple)]
            operands = operands[:4] + [op[1] for op in tagged]
            specs = specs + [op[2] for op in tagged]
            names = [op[0] for op in tagged]

            def wrapped(q, k, v, vl, *rest):
                by_name = dict(zip(names, rest))
                return kernel(q, k, v, valid_len=vl,
                              alibi_slopes=by_name.get("alibi"),
                              seg_starts=by_name.get("segs"))

            return shard_map(
                wrapped, mesh=mesh, in_specs=tuple(specs),
                out_specs=heads, check_vma=False,
            )(*operands)
        return kernel(q, k, v, valid_len=vl, alibi_slopes=alibi_slopes,
                      seg_starts=seg_starts)
    return prefill_attention_xla(q, k, v, scale, valid_len, window=window,
                                 alibi_slopes=alibi_slopes,
                                 seg_starts=seg_starts)


def prefill_attention_xla(
    q: jax.Array,  # [T, H, Dh]
    k: jax.Array,  # [T, Hkv, Dh]
    v: jax.Array,  # [T, Hkv, Dh]
    scale: float,
    valid_len: jax.Array | None = None,  # scalar int: tokens < valid_len attend
    window: int = 0,  # >0: attend to at most the previous `window` tokens
    alibi_slopes: jax.Array | None = None,  # [H] f32 per-head bias slopes
    seg_starts: jax.Array | None = None,  # [max_segs] i32 packed-prefill starts
) -> jax.Array:
    """Causal self-attention over a single (padded) prompt.

    Prompts are padded up to a bucket length; padding tokens still flow
    through the math (static shapes) but their K/V are masked out for real
    tokens' queries via the causal mask, and their own outputs are discarded
    by the caller.

    With ``seg_starts`` (packed prefill) the mask is block-diagonal
    causal: token p belongs to segment ``sum(p >= seg_starts) `` and only
    attends within it.  Padding tokens land in the last segment, but
    their keys are already excluded by valid_len and their query rows are
    discarded by the caller.
    """
    t, num_heads, head_dim = q.shape
    num_kv = k.shape[1]
    q_per_kv = num_heads // num_kv

    qh = q.reshape(t, num_kv, q_per_kv, head_dim).astype(jnp.float32)
    kh = k.astype(jnp.float32)
    vh = v.astype(jnp.float32)

    # [num_kv, q_per_kv, Tq, Tk]
    scores = jnp.einsum("tkgd,skd->kgts", qh, kh) * scale
    if alibi_slopes is not None:
        # HF bloom convention: score(q_i, k_j) += slope_h * j (the
        # row-constant -slope_h*i term cancels in the softmax)
        slopes = alibi_slopes.reshape(num_kv, q_per_kv).astype(jnp.float32)
        scores = scores + (
            slopes[:, :, None, None]
            * jnp.arange(t, dtype=jnp.float32)[None, None, None, :]
        )
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))
    mask = causal
    if window > 0:
        # band mask: query i sees keys (i-window, i] (HF mistral
        # convention — the diagonal plus window-1 predecessors)
        offsets = jnp.arange(t)[:, None] - jnp.arange(t)[None, :]
        mask = mask & (offsets < window)
    if seg_starts is not None:
        # segment of token p = how many segment starts are <= p
        seg = (
            jnp.arange(t)[:, None] >= seg_starts[None, :].astype(jnp.int32)
        ).sum(axis=1)
        mask = mask & (seg[:, None] == seg[None, :])
    if valid_len is not None:
        mask = mask & (jnp.arange(t) < valid_len)[None, :]
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # fully-masked rows (bucket padding beyond valid_len+window) softmax
    # to NaN, and 0·NaN in the value contraction would poison EVERY row
    # at the next layer (padding rows feed layer n+1's K/V); exact zeros
    # keep padding outputs finite (0) and valid rows untouched
    probs = jnp.where(mask[None, None], probs, 0.0)
    out = jnp.einsum("kgts,skd->tkgd", probs, vh)
    return out.reshape(t, num_heads, head_dim).astype(q.dtype)


def chunked_prefill_attention(
    q: jax.Array,  # [T, H, Dh] one chunk's queries
    k_cache: jax.Array,  # [Hkv, num_slots, Dh]
    v_cache: jax.Array,
    block_table: jax.Array,  # [max_blocks] this sequence's page table
    start_pos: jax.Array,  # scalar: context tokens before this chunk
    valid_len: jax.Array,  # scalar: real tokens in the chunk
    block_size: int,
    scale: float,
    mesh=None,
    window: int = 0,
    alibi_slopes: jax.Array | None = None,  # [H] f32 (bloom lineage)
    kv_scales: tuple | None = None,  # ([Hkv, pages] f32 x2) quantized KV
) -> jax.Array:
    """Causal chunk-vs-paged-context attention (the chunked-prefill and
    prefix-cache-resume hot path).

    TPU: dedicated Pallas kernel — each context page is read once per
    (kv head, query block) instead of once per query token.  Fallback:
    the decode formulation (each query as a batch row with its own
    context length), which is what the kernel's numerics are pinned to.
    With quantized KV (``kv_scales`` set, ops/kv_quant.py) the gather
    formulation runs everywhere — this is the legacy solo planner's
    path only (prompt-logprob heads); the ragged serving kernel has its
    own in-register dequant.
    """
    if _use_pallas() and kv_scales is None:
        from vllm_tgis_adapter_tpu.ops import pallas_attention

        kernel = functools.partial(
            pallas_attention.chunked_prefill_attention,
            block_size=block_size,
            scale=scale,
            window=window,
            interpret=_pallas_interpret(),
        )
        if mesh is not None:
            from jax.sharding import PartitionSpec as P

            heads = P(None, "tp", None)
            cache = P("tp", None, None)
            operands = [q, k_cache, v_cache, block_table,
                        jnp.asarray(start_pos, jnp.int32),
                        jnp.asarray(valid_len, jnp.int32)]
            specs = [heads, cache, cache, P(), P(), P()]
            if alibi_slopes is not None:
                operands.append(alibi_slopes)
                specs.append(P("tp"))

            def wrapped(q, kc, vc, bt, sp, vl, *rest):
                return kernel(q, kc, vc, bt, sp, vl,
                              alibi_slopes=rest[0] if rest else None)

            return shard_map(
                wrapped, mesh=mesh, in_specs=tuple(specs),
                out_specs=heads, check_vma=False,
            )(*operands)
        return kernel(q, k_cache, v_cache, block_table, start_pos,
                      valid_len, alibi_slopes=alibi_slopes)
    # XLA fallback: every chunk query becomes a decode row with context
    # length position+1 (exact same semantics, gather-based)
    t = q.shape[0]
    local = jnp.arange(t, dtype=jnp.int32)
    positions = jnp.asarray(start_pos, jnp.int32) + local
    ctx_lens = jnp.where(local < valid_len, positions + 1, 1)
    tables = jnp.broadcast_to(block_table[None, :], (t, block_table.shape[0]))
    return paged_decode_attention_xla(
        q, k_cache, v_cache, tables, ctx_lens, block_size, scale,
        window=window, alibi_slopes=alibi_slopes, kv_scales=kv_scales,
    )


def paged_decode_attention_xla(
    q: jax.Array,  # [B, H, Dh]
    k_cache: jax.Array,  # [Hkv, num_slots, Dh] head-leading
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks] int32 page ids (-1 pad)
    context_lens: jax.Array,  # [B] int32, tokens of context incl. current
    block_size: int,
    scale: float,
    window: int = 0,  # >0: attend to at most the last `window` tokens
    alibi_slopes: jax.Array | None = None,  # [H] f32 per-head bias slopes
    kv_scales: tuple | None = None,  # ([Hkv, pages] f32 x2) quantized KV
) -> jax.Array:
    """One-token-per-sequence attention against the paged cache.

    Gather-based XLA implementation: materialises each sequence's pages as
    ``[B, max_blocks * block_size]`` rows, masks beyond ``context_len``.
    With quantized KV (``kv_scales`` from ops/kv_quant.py) the gathered
    page values multiply by their per-(head, page) scale right after the
    gather — the dequant stays on the gathered working set, never the
    whole cache.
    """
    b, num_heads, head_dim = q.shape
    max_blocks = block_tables.shape[1]
    num_kv = k_cache.shape[0]
    q_per_kv = num_heads // num_kv
    s = max_blocks * block_size

    # [B, S] flat slot index per in-context token position
    slot_idx = (
        block_tables[:, :, None] * block_size
        + jnp.arange(block_size)[None, None, :]
    ).reshape(b, s)
    # pages with id -1 produce negative slots; take(mode='fill') would give
    # garbage — clamp and rely on the length mask instead
    gather_idx = jnp.clip(slot_idx, 0, k_cache.shape[1] - 1)

    keys = jnp.take(k_cache, gather_idx, axis=1).astype(jnp.float32)  # [Hkv,B,S,Dh]
    values = jnp.take(v_cache, gather_idx, axis=1).astype(jnp.float32)
    if kv_scales is not None:
        k_scale, v_scale = kv_scales
        page_idx = gather_idx // block_size  # [B, S] physical page ids
        keys = keys * jnp.take(
            k_scale.astype(jnp.float32), page_idx, axis=1
        )[..., None]
        values = values * jnp.take(
            v_scale.astype(jnp.float32), page_idx, axis=1
        )[..., None]

    qh = q.reshape(b, num_kv, q_per_kv, head_dim).astype(jnp.float32)
    scores = jnp.einsum("bkgd,kbsd->bkgs", qh, keys) * scale
    if alibi_slopes is not None:
        # position index s IS the sequence position (block j of the table
        # covers positions [j*bs, (j+1)*bs)); same bias as prefill
        slopes = alibi_slopes.reshape(num_kv, q_per_kv).astype(jnp.float32)
        scores = scores + (
            slopes[None, :, :, None]
            * jnp.arange(s, dtype=jnp.float32)[None, None, None, :]
        )
    length_mask = jnp.arange(s)[None, :] < context_lens[:, None]  # [B, S]
    if window > 0:
        # sliding window: only the last `window` in-context positions
        length_mask = length_mask & (
            jnp.arange(s)[None, :] >= context_lens[:, None] - window
        )
    scores = jnp.where(length_mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,kbsd->bkgd", probs, values)
    return out.reshape(b, num_heads, head_dim).astype(q.dtype)
