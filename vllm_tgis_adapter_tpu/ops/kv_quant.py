"""Quantized KV pages: int8/fp8 storage with per-page-per-head scales.

Decode throughput on TPU is HBM-bandwidth- and KV-capacity-bound, and
since PRs 9-11 the KV page is the unit of *everything* — the device
pool, the host prefix tier, decode checkpoints, and prefill→decode
handoffs all move whole pages.  Halving page bytes therefore doubles
effective capacity across the entire stack at once: more pages per HBM
budget → bigger ragged batches → direct tok/s (ROADMAP item 5; the
Gemma-on-TPU serving comparison in PAPERS.md is the low-precision
precedent).  This module implements the storage scheme and every
quantize/dequantize primitive the rest of the stack composes
(docs/QUANTIZATION.md):

* **Storage.**  ``--kv-quantization int8`` stores pages as symmetric
  int8 (``q ∈ [-127, 127]``); ``fp8`` stores ``float8_e4m3fn`` (max
  normal 448).  The cache becomes a :class:`QuantizedKVCache` — the
  quantized ``data`` array in the familiar head-leading
  ``[L, Hkv, num_slots, Dh]`` layout plus a f32 ``scale`` sidecar
  ``[L, Hkv, num_pages]``: ONE dequant scale per (layer, kv head,
  physical page).  ``none`` (the default) keeps plain arrays and is
  byte-identical to the pre-quantization engine — none of the helpers
  below emit a single different op for raw arrays.

* **Scale discipline (the token-identity anchor).**  A page's scale is
  (re)set exactly when its FIRST slot is written: from that row's
  per-head ``|amax|`` times a fixed headroom margin.  Every write in
  the same dispatch — and every later append to the page — quantizes
  with the post-update scale (values past the range clip).  Because a
  position's K/V is a pure function of the token history, the scale is
  REPRODUCIBLE no matter which path writes slot 0 (solo prefill, a
  ragged chunk, a decode step, a speculative verify span, or a
  checkpoint-resume tail recompute): demote→promote through the host
  tier, decode checkpoint/resume, and prefill→decode handoffs all stay
  token-identical under quantization.  A running per-page amax would
  be tighter but is NOT append-consistent — growing the scale would
  silently rescale previously stored integers.

* **Dequantization at the page read.**  The Pallas ragged kernel
  multiplies each DMA'd page tile by its one scale scalar in-register
  (ops/ragged_attention.py); the XLA reference path multiplies after
  the page gather (ops/attention.py ``paged_decode_attention_xla``).
  Softmax stays f32 either way, so quantization only perturbs the K/V
  operands, never the accumulation.

* **Page movement.**  ``gather_kv_page`` / ``restore_kv_page`` are the
  jitted per-page entry points the host tier and checkpoint paths ride
  (engine/runner.py wraps them in ``track_jit``): one fixed
  block-shaped program each, quantized or not — the scale column
  travels WITH the page, so tier entries, checkpoints and role
  handoffs carry the sidecar for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: accepted --kv-quantization values (engine/config.py validates).
SCHEMES = ("none", "int8", "fp8")

#: headroom multiplier on the scale-setting row's |amax|: later tokens
#: appended to the page clip only when they exceed MARGIN x the first
#: token's per-head amax.  Costs one effective bit of int8 precision;
#: K/V magnitudes are near-stationary across positions, so the clip
#: rate stays negligible (tests/test_kv_quant.py roundtrip bounds).
SCALE_MARGIN = 2.0

_EPS = 1e-8


def storage_dtype(scheme: str):
    """Quantized storage dtype for ``scheme`` (``none`` → None)."""
    if scheme == "int8":
        return jnp.int8
    if scheme == "fp8":
        return jnp.float8_e4m3fn
    return None


def qmax_for(dtype) -> float:
    """Largest representable magnitude quantization targets."""
    if jnp.dtype(dtype) == jnp.dtype(jnp.int8):
        return 127.0
    return 448.0  # float8_e4m3fn max normal


def scale_bytes_per_page(num_layers: int, kv_heads: int) -> int:
    """Sidecar bytes ONE page adds (both caches): 2 x [L, Hkv] f32."""
    return 2 * num_layers * kv_heads * 4


@jax.tree_util.register_pytree_node_class
class QuantizedKVCache:
    """One quantized K (or V) cache: ``data`` + per-page ``scale``.

    ``data``  — ``[L, Hkv, num_slots, Dh]`` int8 / float8_e4m3fn
    ``scale`` — ``[L, Hkv, num_pages]`` f32, dequant multiplier per
    physical page (``num_pages = num_slots // block_size``); 0 marks a
    never-written page (its garbage content is masked by context
    length everywhere it could be read).

    Registered as a pytree so it flows through ``jax.jit`` / ``scan``
    carries / donation exactly like the raw array it replaces; the
    ``shape`` / ``dtype`` properties keep the handful of geometry reads
    (``k_cache.shape[2]``) working unchanged.

    ``floor`` (optional, ``[L, Hkv]`` f32) is the CALIBRATED per-layer
    per-head scale floor loaded from checkpoints that ship
    ``k_scale``/``v_scale`` tensors (engine/weights.py): models whose
    K/V outliers punish pure-amax scaling set their page scales to
    ``max(amax-derived, floor)`` at the slot-0 write.  None (the
    default, and every checkpoint without the tensors) is bit-identical
    to the pre-floor engine.
    """

    __slots__ = ("data", "scale", "block_size", "floor")

    def __init__(self, data, scale, block_size: int, floor=None):
        self.data = data
        self.scale = scale
        self.block_size = block_size
        self.floor = floor

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def tree_flatten(self):
        return (self.data, self.scale, self.floor), self.block_size

    @classmethod
    def tree_unflatten(cls, block_size, children):
        data, scale, floor = children
        return cls(data, scale, block_size, floor)


def is_quantized(cache) -> bool:
    return isinstance(cache, QuantizedKVCache)


def make_kv_cache(
    shape: tuple, dtype, scheme: str = "none", block_size: int = 16,
    scale_floor=None,
):
    """Zeroed cache in the layout ``scheme`` dictates.

    ``none`` returns the plain zeros array the engine always built —
    byte-identical off.  int8/fp8 return a :class:`QuantizedKVCache`
    with an all-zero scale sidecar (every page starts "never written").
    ``scale_floor`` ([L, Hkv] f32 or None) attaches the calibrated
    per-head scale floor from quantization-aware checkpoints.
    """
    qdtype = storage_dtype(scheme)
    if qdtype is None:
        return jnp.zeros(shape, dtype=dtype)
    num_layers, kv_heads, num_slots, _ = shape
    return QuantizedKVCache(
        jnp.zeros(shape, dtype=qdtype),
        jnp.zeros(
            (num_layers, kv_heads, num_slots // block_size), jnp.float32
        ),
        block_size,
        floor=(
            None
            if scale_floor is None
            else jnp.asarray(scale_floor, jnp.float32)
        ),
    )


def layer_data(cache, i):
    """The per-layer array attention kernels read (quantized or not)."""
    if is_quantized(cache):
        return cache.data[i]
    return cache[i]


def layer_scales(k_cache, v_cache, i):
    """``kv_scales`` operand for the attention ops: ``(k_scale[i],
    v_scale[i])`` (each ``[Hkv, num_pages]`` f32) or None when the
    caches are unquantized."""
    if is_quantized(k_cache):
        return (k_cache.scale[i], v_cache.scale[i])
    return None


def dequantize(x, scale):
    """Dequantize gathered page values: ``x * scale`` in f32.

    ``scale`` must broadcast against ``x`` with the trailing head-dim
    axis already expanded by the caller (one scale per page covers
    every slot and every head-dim lane of that page's tile).
    """
    return x.astype(jnp.float32) * scale


def _quantize_values(x, qdtype, qmax):
    """f32 ``x`` (already divided by scale) → storage dtype, saturating."""
    if jnp.dtype(qdtype) == jnp.dtype(jnp.int8):
        return jnp.clip(jnp.round(x), -qmax, qmax).astype(jnp.int8)
    # float8_e4m3fn has no inf: out-of-range casts become NaN, so clip
    # to the max normal first (saturation semantics, like the MXU)
    return jnp.clip(x, -qmax, qmax).astype(qdtype)


def scatter_layer(cache, i, safe_slots, vals):
    """Scatter this step's K (or V) rows into layer ``i`` of ``cache``.

    ``vals`` is ``[T, Hkv, Dh]``; ``safe_slots`` is ``[T]`` with padding
    rows remapped to ``num_slots`` (positive out-of-bounds, dropped by
    the scatter).  For a raw cache this is EXACTLY the historical
    ``cache.at[i, :, safe_slots].set(vals.astype(dtype), mode="drop")``.

    For a quantized cache the scale sidecar updates first: every page
    whose slot 0 is among this dispatch's writes re-sets its scale from
    that row's per-head |amax| (x SCALE_MARGIN), then ALL rows quantize
    with the post-update scales and scatter.  One slot is written at
    most once per dispatch (spans are disjoint), so the scatter-max
    candidates never race.
    """
    if not is_quantized(cache):
        return cache.at[i, :, safe_slots].set(
            vals.astype(cache.dtype), mode="drop"
        )
    data, scale = cache.data, cache.scale
    bs = cache.block_size
    num_pages = scale.shape[2]
    qmax = qmax_for(data.dtype)
    pages = safe_slots // bs  # [T]; padding rows land OOB and drop
    vt = jnp.swapaxes(vals.astype(jnp.float32), 0, 1)  # [Hkv, T, Dh]
    amax = jnp.max(jnp.abs(vt), axis=-1)  # [Hkv, T]
    setter = (safe_slots % bs == 0).astype(jnp.int32)  # [T]
    # per-page candidate amax from the slot-0 rows of THIS dispatch
    # (at most one such row per page — spans write each slot once)
    cand = (
        jnp.zeros((vt.shape[0], num_pages), jnp.float32)
        .at[:, pages]
        .max(amax * setter[None, :].astype(jnp.float32), mode="drop")
    )
    fresh = (
        jnp.zeros((num_pages,), jnp.int32)
        .at[pages]
        .max(setter, mode="drop")
    )
    set_scale = jnp.maximum(cand * SCALE_MARGIN, _EPS) / qmax
    if cache.floor is not None:
        # quantization-aware checkpoint (docs/QUANTIZATION.md
        # "Calibrated scales"): the calibrated per-head scale FLOORS
        # the amax-derived value at the slot-0 write — outlier-prone
        # heads keep the headroom the calibration measured, while the
        # floor itself never shrinks an amax that genuinely exceeds it
        set_scale = jnp.maximum(set_scale, cache.floor[i][:, None])
    layer_scale = jnp.where(
        fresh[None, :] == 1,
        set_scale,
        scale[i],
    )
    scale = scale.at[i].set(layer_scale)
    row_scale = jnp.take(
        layer_scale, jnp.clip(pages, 0, num_pages - 1), axis=1
    )  # [Hkv, T]; padding rows read garbage their scatter then drops
    q = _quantize_values(
        vt / jnp.maximum(row_scale, _EPS)[..., None], data.dtype, qmax
    )
    data = data.at[i, :, safe_slots].set(
        jnp.swapaxes(q, 0, 1), mode="drop"
    )
    return QuantizedKVCache(data, scale, bs, floor=cache.floor)


# ------------------------------------------------- per-page movement ops
#
# The jitted entry points the host KV tier, decode checkpoints and
# prefill→decode handoffs ride (engine/runner.py gather_kv_block /
# restore_kv_block wrap these in track_jit "gather_kv" / "scatter_kv"):
# ``idx`` is always exactly one page's block_size slots, so each holds
# ONE compiled shape forever, quantized or not.  Registered in
# tools/tpulint/config.py JIT_REGISTRY.


def gather_kv_page(k_cache, v_cache, idx):
    """Gather one page from both caches for host-tier demotion.

    Raw caches return ``(k, v)`` slot gathers — the historical
    contract.  Quantized caches return ``(k, v, k_scale, v_scale)``
    where the scale columns are ``[L, Hkv]`` f32: the sidecar travels
    with the page into the tier entry (and through checkpoints and
    role handoffs, which reference the same entries).
    """
    if not is_quantized(k_cache):
        return (
            jnp.take(k_cache, idx, axis=2),
            jnp.take(v_cache, idx, axis=2),
        )
    page = idx[0] // k_cache.block_size
    return (
        jnp.take(k_cache.data, idx, axis=2),
        jnp.take(v_cache.data, idx, axis=2),
        k_cache.scale[:, :, page],
        v_cache.scale[:, :, page],
    )


def restore_kv_page(k_cache, v_cache, idx, *arrays):
    """Scatter one promoted/checkpointed page back into both caches.

    ``arrays`` is exactly what ``gather_kv_page`` produced (the tier
    stores and re-stages it verbatim, so the quantized roundtrip is
    BIT-exact — no requantization, token identity preserved).  Raw
    caches scatter values with a dtype cast, the historical behavior.
    """
    if not is_quantized(k_cache):
        k_host, v_host = arrays
        return (
            k_cache.at[:, :, idx, :].set(
                k_host.astype(k_cache.dtype), mode="drop"
            ),
            v_cache.at[:, :, idx, :].set(
                v_host.astype(v_cache.dtype), mode="drop"
            ),
        )
    k_host, v_host, k_scale, v_scale = arrays
    page = idx[0] // k_cache.block_size
    bs = k_cache.block_size
    return (
        QuantizedKVCache(
            k_cache.data.at[:, :, idx, :].set(
                k_host.astype(k_cache.data.dtype), mode="drop"
            ),
            k_cache.scale.at[:, :, page].set(k_scale),
            bs,
            floor=k_cache.floor,
        ),
        QuantizedKVCache(
            v_cache.data.at[:, :, idx, :].set(
                v_host.astype(v_cache.data.dtype), mode="drop"
            ),
            v_cache.scale.at[:, :, page].set(v_scale),
            bs,
            floor=v_cache.floor,
        ),
    )
