"""Unified paged HBM arena: ONE block budget for KV pages + adapter shards.

Before this module, the device's two big consumers were separately
budgeted: ``kv_cache.BlockAllocator`` owned the KV page pool and
``adapter_pool.AdapterPool`` owned fixed adapter slots — so HBM headroom
could not flow between a KV-heavy RAG burst and an adapter-heavy
multi-tenant burst, the half of S-LoRA's insight the repo had not yet
adopted (PAPERS.md; ROADMAP item 3).  The arena merges the two into one
paged budget with unified LRU + pinning semantics (docs/MEMORY.md):

* **Typed pages, single budget.**  Every page of the budget is either a
  KV page (owned by the allocator's refcounts / prefix cache) or an
  adapter-shard page (charged when an adapter becomes device-resident).
  An adapter's charge is priced by its TRUE rank bucket — a rank-8
  adapter on a ``--max-lora-rank 64`` server charges ~1/8th of the
  padded cost — so the heterogeneous-rank gathered matmul's storage
  accounting and the budget agree (engine/lora.py ``adapter_page_cost``).

* **Unified LRU scoring.**  When either workload needs pages, the arena
  reclaims whichever cold resident scores worst: freed-but-registered
  KV pages carry their park timestamp (``BlockAllocator``'s cached-free
  LRU) and unpinned resident adapters carry their last-touch timestamp
  (``AdapterPool._lru``); the older one is evicted first.  Existing
  safety semantics are preserved verbatim — KV evictions still demote
  into the host tier through ``evict_hook``, adapter evictions fall
  back to the host registry (weights stay in ``LoRAManager`` host RAM,
  or the disk tier beneath it), pinned adapters and refcounted KV pages
  are never touched, and the prefix-cache hash walk is unchanged.

* **Charge = physical reservation.**  An adapter charge RESERVES page
  ids out of the allocator (``allocate``), so ``num_free``, the
  scheduler's ``can_allocate`` checks, preemption pressure and the
  /debug/state occupancy all see one truthful number without learning
  anything about adapters.  The reserved ids are idle while charged
  (the shard bytes physically live in the pool's stacked tensors, whose
  boot-time cap ``resolve_num_blocks`` already prices); releasing the
  charge returns them to the KV side.

A floor (``min_kv_reserve``) keeps adapter pressure from starving the
KV side below one max-length sequence — past it, adapter prefetches
simply park (the existing adapter-gate contract) until KV work drains.
"""

from __future__ import annotations

import time
from typing import Optional

from vllm_tgis_adapter_tpu.logging import init_logger

logger = init_logger(__name__)


class UnifiedArena:
    """Typed-page accounting over ONE BlockAllocator's block budget."""

    #: an adapter is evictable under CROSS-type pressure only after
    #: this many seconds idle.  Without the floor, a transient KV
    #: shortfall evicts the LRU-oldest adapter even when it was touched
    #: milliseconds ago (hot round-robin tenants make SOMEONE oldest),
    #: and the very next request re-streams it — a ping-pong that trades
    #: a cheap page preemption/recompute for an expensive host→device
    #: adapter transfer, over and over (the ISSUE 8 churn gate caught
    #: exactly this).  Genuinely cold adapters (the multi-tenant burst
    #: tail) still fund KV demand; hot ones keep residency and KV falls
    #: back to its pre-arena preemption behavior.
    ADAPTER_MIN_IDLE_S = 2.0

    def __init__(
        self,
        allocator,  # noqa: ANN001 — kv_cache.BlockAllocator
        kv_page_bytes: int,
        min_kv_reserve: int = 0,
        adapter_budget_pages: int = 0,
    ):
        self.allocator = allocator
        self.kv_page_bytes = max(1, int(kv_page_bytes))
        # pages the KV side is guaranteed even under full adapter
        # pressure: one max-length sequence by default so the
        # scheduler's "prompt can never fit" refusal threshold is
        # unchanged by adapter residency — but never more than HALF
        # the pool (a tiny pool must still admit adapters; liveness
        # beats a reserve nobody sized deliberately)
        self.min_kv_reserve = min(
            int(min_kv_reserve), max(0, allocator.num_blocks // 2)
        )
        # the adapter side's OWN budget, in KV-page units: the
        # boot-time reservation the physical slot stacks already carve
        # out of HBM (kv_cache._lora_stack_bytes — resolve_num_blocks
        # subtracts it before sizing the KV pool).  Charges consume
        # this reservation FIRST; only the overflow BORROWS page ids
        # from the KV allocator.  Charging everything out of the KV
        # pool instead would double-count the reservation and put a
        # previously comfortable pool under permanent pressure — the
        # hot-adapter eviction ping-pong the ISSUE 8 churn gate
        # caught.  With today's padded slot stacks the true-rank sum
        # never exceeds the padded cap, so borrowing engages only when
        # callers size the budget BELOW the cap (and for the future
        # page-granular shard storage — ROADMAP item 3a).
        self.adapter_budget_pages = max(0, int(adapter_budget_pages))
        self.adapter_reserve_used = 0
        # pools drawing adapter pages from this arena (one per runner;
        # dp replicas each have their own arena over their own pool)
        self._pools: list = []
        # (pool_id, adapter_name) -> (reserve_pages, borrowed page ids)
        self._charges: dict[tuple[int, str], tuple[int, list[int]]] = {}
        self.adapter_blocks = 0
        self.borrowed_blocks = 0
        # lifetime stats (debug_state / tests)
        self.adapter_charges = 0
        self.adapter_releases = 0
        self.kv_reclaims = 0  # adapters evicted under KV pressure
        self.adapter_funded_by_kv = 0  # cold KV pages consumed by charges
        self._reclaiming = False

    # ------------------------------------------------------------- wiring

    def attach_pool(self, pool) -> None:  # noqa: ANN001 — AdapterPool
        if pool not in self._pools:
            self._pools.append(pool)

    # ----------------------------------------------------- adapter charges

    def charge_adapter(self, pool, name: str, pages: int) -> bool:  # noqa: ANN001
        """Charge ``pages`` of the budget for one adapter becoming
        device-resident: the adapter reservation funds it first, and
        only the OVERFLOW borrows page ids from the KV allocator — in
        unified-LRU order, free pages → whichever of (coldest cached
        KV page, coldest idle unpinned adapter) is older, KV evictions
        demoting into the host tier via the allocator's evict hook.
        Returns False (the request parks, the existing adapter-gate
        contract) when the overflow cannot be funded without dropping
        the KV side below ``min_kv_reserve`` or touching pinned/live
        pages."""
        key = (id(pool), name)
        if key in self._charges:
            return True
        pages = max(1, int(pages))
        alloc = self.allocator
        reserve_free = self.adapter_budget_pages - self.adapter_reserve_used
        from_reserve = min(pages, max(0, reserve_free))
        borrow = pages - from_reserve
        if borrow > alloc.num_blocks - self.min_kv_reserve:
            # this adapter could NEVER be charged, even alone — the
            # whole budget is smaller than one adapter.  Grant an
            # uncharged residency instead of parking its requests
            # forever: liveness exactly as pre-arena, with the
            # shortfall visible in the stats.
            logger.warning(
                "arena: adapter %s needs %d pages but the budget caps "
                "adapter residency at %d reserved + %d borrowable — "
                "granting UNCHARGED residency",
                name, pages, self.adapter_budget_pages,
                alloc.num_blocks - self.min_kv_reserve,
            )
            self._charges[key] = (0, [])
            self.adapter_charges += 1
            return True
        blocks: list[int] = []
        if borrow:
            if (
                self.borrowed_blocks + borrow
                > alloc.num_blocks - self.min_kv_reserve
            ):
                # borrow cap: evicting colder BORROWING adapters can
                # still fund this (hotter displaces colder)
                if not self._evict_adapters_until(
                    lambda: self.borrowed_blocks + borrow
                    <= alloc.num_blocks - self.min_kv_reserve,
                    skip=key,
                ):
                    return False
                reserve_free = (
                    self.adapter_budget_pages - self.adapter_reserve_used
                )
                from_reserve = min(pages, max(0, reserve_free))
                borrow = pages - from_reserve
        if borrow:
            # cross-type LRU: prefer evicting an idle unpinned
            # BORROWING adapter COLDER than the allocator's coldest
            # cached page before allocate() consumes that (warmer) KV
            # content — reserve-only adapters free no allocator pages,
            # so evicting them here would burn re-streams for nothing
            while len(alloc._free) < borrow:  # noqa: SLF001
                kv_ts = alloc.oldest_cached_ts()
                victim = self._coldest_adapter(
                    skip=key, borrowers_only=True
                )
                if victim is not None and (
                    kv_ts is None or victim[2] < kv_ts
                ):
                    self._evict_adapter(victim[0], victim[1])
                    continue
                break  # cached KV (if any) is colder; allocate() takes it
            if not alloc.can_allocate(borrow):
                # everything left is refcounted live KV: park
                return False
            before_cached = len(alloc._cached_free)  # noqa: SLF001
            blocks = alloc.allocate(borrow)
            self.adapter_funded_by_kv += max(
                0, before_cached - len(alloc._cached_free)  # noqa: SLF001
            )
        self._charges[key] = (from_reserve, blocks)
        self.adapter_reserve_used += from_reserve
        self.adapter_blocks += pages
        self.borrowed_blocks += len(blocks)
        self.adapter_charges += 1
        return True

    def release_adapter(self, pool, name: str) -> None:  # noqa: ANN001
        """Return one adapter's charge to the budget (device eviction /
        invalidation / pool teardown)."""
        got = self._charges.pop((id(pool), name), None)
        if got is None:
            return
        from_reserve, blocks = got
        self.adapter_reserve_used -= from_reserve
        self.adapter_blocks -= from_reserve + len(blocks)
        self.borrowed_blocks -= len(blocks)
        self.adapter_releases += 1
        if blocks:
            # epoch-bypassing release: borrowed pages were never
            # writable by KV programs (kv_cache.free_reserved)
            self.allocator.free_reserved(blocks)

    def release_pool(self, pool) -> None:  # noqa: ANN001
        """Drop every charge a (dying) pool holds."""
        for key in [k for k in self._charges if k[0] == id(pool)]:
            from_reserve, blocks = self._charges.pop(key)
            self.adapter_reserve_used -= from_reserve
            self.adapter_blocks -= from_reserve + len(blocks)
            self.borrowed_blocks -= len(blocks)
            if blocks:
                self.allocator.free_reserved(blocks)
        self._pools = [p for p in self._pools if p is not pool]

    # --------------------------------------------------------- KV pressure

    def fund_kv(self, need: int) -> None:
        """KV demand (``BlockAllocator.can_allocate`` shortfall): evict
        cold idle unpinned adapters HOLDING BORROWED PAGES — in
        unified-LRU order against the allocator's own cached pages —
        until ``need`` pages are allocatable or no such adapter
        remains.  Reservation-backed charges yield nothing the KV side
        can use, so they are never evicted for KV; the allocator then
        proceeds (or the scheduler preempts) exactly as before."""
        if self._reclaiming or not self.borrowed_blocks:
            return
        alloc = self.allocator
        self._reclaiming = True
        try:
            while not alloc.can_allocate(need):
                victim = self._coldest_adapter(borrowers_only=True)
                if victim is None:
                    return
                self._evict_adapter(victim[0], victim[1])
            # free+cached now suffice; still prefer evicting borrowers
            # COLDER than the cached KV content allocate() would destroy
            while len(alloc._free) < need:  # noqa: SLF001
                kv_ts = alloc.oldest_cached_ts()
                victim = self._coldest_adapter(borrowers_only=True)
                if victim is None or (
                    kv_ts is not None and kv_ts <= victim[2]
                ):
                    return
                self._evict_adapter(victim[0], victim[1])
        finally:
            self._reclaiming = False

    # ------------------------------------------------------------ eviction

    def _coldest_adapter(
        self, skip: Optional[tuple] = None, borrowers_only: bool = False
    ) -> Optional[tuple]:
        """(pool, name, last_touch) of the coldest evictable charged
        adapter — honoring pins AND the idle floor — or None.
        ``borrowers_only`` restricts to charges holding borrowed KV
        pages (the only evictions that help a KV shortfall)."""
        best = None
        horizon = time.monotonic() - self.ADAPTER_MIN_IDLE_S
        for pool in self._pools:
            manager = getattr(pool, "manager", None)
            for name in pool.resident_names():
                if skip is not None and (id(pool), name) == skip:
                    continue
                charge = self._charges.get((id(pool), name))
                if charge is None:
                    continue
                if borrowers_only and not charge[1]:
                    continue
                if manager is not None and manager.pinned(name):
                    continue
                ts = pool.last_touch(name)
                if ts > horizon:
                    continue  # hot: cross-type eviction would ping-pong
                if best is None or ts < best[2]:
                    best = (pool, name, ts)
        return best

    def _evict_adapter(self, pool, name: str) -> None:  # noqa: ANN001
        charge = self._charges.get((id(pool), name), (0, []))
        logger.info(
            "arena: evicting cold adapter %s (%d pages back to the "
            "unified budget)",
            name, charge[0] + len(charge[1]),
        )
        self.kv_reclaims += 1
        # the pool's eviction path calls release_adapter back into us
        pool.evict_resident(name)

    def _evict_adapters_until(self, done, skip=None) -> bool:  # noqa: ANN001
        while not done():
            victim = self._coldest_adapter(skip=skip)
            if victim is None:
                return False
            self._evict_adapter(victim[0], victim[1])
        return True

    # -------------------------------------------------------------- stats

    @property
    def num_blocks(self) -> int:
        return self.allocator.num_blocks

    def debug_state(self) -> dict:
        """``arena`` section of the per-replica /debug/state."""
        return {
            "total_blocks": self.allocator.num_blocks,
            "adapter_blocks": self.adapter_blocks,
            "adapter_budget_pages": self.adapter_budget_pages,
            "adapter_reserve_used": self.adapter_reserve_used,
            "borrowed_blocks": self.borrowed_blocks,
            "kv_free_blocks": self.allocator.num_free,
            "min_kv_reserve": self.min_kv_reserve,
            "charged_adapters": sorted(
                name for (_pid, name) in self._charges
            ),
            "adapter_charges": self.adapter_charges,
            "adapter_releases": self.adapter_releases,
            "kv_reclaims": self.kv_reclaims,
            "adapter_funded_by_kv": self.adapter_funded_by_kv,
        }

    def observe(self, replica: int = 0) -> None:
        """Push the typed-page split into the arena gauge."""
        try:
            from vllm_tgis_adapter_tpu import metrics

            alloc = self.allocator
            rep = str(replica)
            metrics.arena_blocks.labels(
                type="adapter", replica=rep
            ).set(self.adapter_blocks)
            # only BORROWED adapter pages came out of the allocator
            # (reserve-funded charges never touched it), so kv_used
            # subtracts borrowed_blocks, not the whole adapter charge
            metrics.arena_blocks.labels(
                type="kv_used", replica=rep
            ).set(alloc.num_blocks - alloc.num_free - self.borrowed_blocks)
            metrics.arena_blocks.labels(
                type="kv_free", replica=rep
            ).set(alloc.num_free)
        except Exception:  # pragma: no cover — telemetry must not raise
            pass
