"""Draft-model speculative decoding (propose γ → verify as a ragged span).

The reference exposes ``--speculative-model`` / ``--num-speculative-tokens``
and delegates the mechanism to its engine
(/root/reference/src/vllm_tgis_adapter/tgis_utils/args.py:164-168,221-231);
this is the TPU-native mechanism itself, composed with the ragged paged
attention data path (docs/ATTENTION.md "Speculative decoding"):

* **propose**: a ``lax.scan`` over γ draft-model decode steps — one device
  dispatch proposes γ tokens per batch row (writing the draft's own paged
  K/V as it goes) and returns the draft's per-position sampling
  distribution q, which rejection-sampling verification needs;
* **verify**: a spec-eligible running row contributes a (γ+1)-token SPAN
  ``[last_token, d₁ … d_γ]`` to the SAME flat ragged stream that carries
  fresh prefill chunks and plain decode rows — the per-sequence span
  descriptors from the Ragged Paged Attention formulation handle a short
  multi-token span natively, and the kernel's causal masking within the
  span yields exactly the verify logits.  One dispatch
  (``runner._ragged_verify_fn``) serves the whole mixed batch; acceptance
  runs on device via ``_rejection_core`` below;
* rejected positions leave stale K/V in both caches, which is safe: the
  next dispatch re-inputs the corrected token at that position and
  overwrites the slot before anything reads it (device work is strictly
  serialized).

Greedy equivalence: the accepted prefix plus the bonus token reproduces
exactly the non-speculative greedy chain — each accepted dᵢ equals the
target argmax given the identical prefix.  Sampled rows (temperature>0,
top-k/top-p, unseeded) verify by REJECTION SAMPLING — accept dᵢ with
prob min(1, p(dᵢ)/q(dᵢ)), resample the residual norm(max(p−q,0)) on
reject — which emits tokens distributed exactly as the target's sampling
distribution (Leviathan et al. 2023).  LoRA rows verify through the
adapted target (per-row ``lora_idx`` rides the stream) while the draft
proposes from base weights.  Rows with state-evolving knobs (repetition
penalty, typical-p, length-penalty/min-tokens, FSM) and SEEDED sampled
rows ride the plain one-token decode span in the same dispatch —
speculation is per-ROW on the ragged path, not per-batch.

Draft/target contract: same tokenizer and vocab size (validated at
boot); the draft shares the target's block tables and slot geometry, so
its cache is simply a second (smaller) set of paged arrays.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

from vllm_tgis_adapter_tpu.logging import init_logger

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.runner import ModelRunner

logger = init_logger(__name__)

_LOG_EVERY = 50  # dispatches between acceptance-rate log lines

# PRNG stream salts: the draft's proposal draws, the acceptance uniforms
# and the residual/bonus draws must be mutually independent streams per
# (request, position) or acceptance correlates with the proposal
_SALT_DRAFT = 1
_SALT_ACCEPT = 2
_SALT_EMIT = 3


def _spec_dist(
    logits: jax.Array,  # [N, V] raw model logits
    temps: jax.Array,  # [N] f32; 0 == greedy row
    top_k: jax.Array,  # [N] i32; <=0 disabled
    top_p: jax.Array,  # [N] f32
) -> jax.Array:
    """Per-row sampling distribution: temperature scale + top-k/top-p
    filter, softmax; greedy rows become exact one-hots so the rejection
    test degenerates to an argmax match for them."""
    import types

    from vllm_tgis_adapter_tpu.engine.sampler import (
        _filter_top_k_top_p_typical,
    )

    greedy = temps <= 0.0
    safe = jnp.where(greedy, 1.0, temps)[:, None]
    scaled = logits.astype(jnp.float32) / safe
    knobs = types.SimpleNamespace(
        top_k=top_k, top_p=top_p, typical_p=jnp.ones_like(top_p)
    )
    probs = jax.nn.softmax(_filter_top_k_top_p_typical(scaled, knobs), -1)
    onehot = jax.nn.one_hot(
        jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=probs.dtype
    )
    return jnp.where(greedy[:, None], onehot, probs)


def _rejection_core(
    logits: jax.Array,  # [B, K, V] target logits over the window
    q_probs: jax.Array,  # [gamma, B, V] draft sampling distributions
    window: jax.Array,  # [B, K] last token + gamma draft proposals
    temps: jax.Array,  # [B]
    top_k: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
    base_key: jax.Array,  # [B] uint32
    gen0: jax.Array,  # [B] tokens generated so far (PRNG position base)
) -> tuple[jax.Array, jax.Array]:
    """Pure rejection-sampling acceptance + emission (Leviathan et al.).

    Accept draft token d_j with prob min(1, p(d_j)/q(d_j)); at the first
    rejection sample from the residual norm(max(p−q, 0)); on full
    acceptance sample the bonus token from p directly.  Greedy rows have
    one-hot p/q, so acceptance degenerates to the argmax match test and
    emission to the target argmax — bit-identical to a greedy verify.
    Returns (emitted [B, K], accepted [B] in 0..gamma).  Factored out of
    the verify program so the distribution-preservation property is
    testable without a model (tests/test_speculative.py).
    """
    b, kw, v = logits.shape
    gamma = kw - 1
    rep = lambda x: jnp.repeat(x, kw, axis=0)  # noqa: E731
    p_probs = _spec_dist(
        logits.reshape(b * kw, v), rep(temps), rep(top_k), rep(top_p)
    ).reshape(b, kw, v)

    d = window[:, 1:]  # [B, gamma] draft proposals
    q_t = jnp.moveaxis(q_probs, 0, 1)  # [B, gamma, V]
    p_d = jnp.take_along_axis(
        p_probs[:, :gamma], d[..., None], axis=-1
    )[..., 0]
    q_d = jnp.take_along_axis(q_t, d[..., None], axis=-1)[..., 0]
    ratio = p_d / jnp.maximum(q_d, 1e-20)

    def u_one(s, p):
        kk = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(s), p), _SALT_ACCEPT
        )
        return jax.random.uniform(kk)

    u = jax.vmap(
        lambda s, g: jax.vmap(lambda j: u_one(s, g + j))(jnp.arange(gamma))
    )(base_key, gen0)  # [B, gamma]
    accept = u < ratio
    accepted = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1
    )  # [B] in 0..gamma

    # emission at the first non-accepted position: residual distribution
    # (or p itself for the bonus token)
    pos_e = jnp.minimum(accepted, gamma)
    p_e = jnp.take_along_axis(p_probs, pos_e[:, None, None], axis=1)[:, 0]
    q_e = jnp.take_along_axis(
        q_t, jnp.minimum(accepted, gamma - 1)[:, None, None], axis=1
    )[:, 0]
    q_e = jnp.where((accepted >= gamma)[:, None], 0.0, q_e)
    resid = jnp.maximum(p_e - q_e, 0.0)
    mass = jnp.sum(resid, axis=-1, keepdims=True)
    dist = jnp.where(mass > 0, resid / jnp.maximum(mass, 1e-30), p_e)
    keys_e = jax.vmap(
        lambda s, p: jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(s), p), _SALT_EMIT
        )
    )(base_key, gen0 + accepted)
    tok_sampled = jax.vmap(jax.random.categorical)(
        keys_e, jnp.log(dist + 1e-30)
    )
    tok_e = jnp.where(
        temps <= 0.0, jnp.argmax(dist, axis=-1), tok_sampled
    ).astype(jnp.int32)

    cols = jnp.arange(kw)[None, :]
    emitted = jnp.where(
        cols < accepted[:, None],
        jnp.pad(d, ((0, 0), (0, 1))),
        tok_e[:, None],
    )  # [B, K]; col j<a: draft token, col a: resampled/bonus
    return emitted, accepted


def _pack_spec_results(emitted, accepted, lp, rank, topn_ids, topn_lp):
    """Merge the verify outputs into ONE int32 buffer so the whole spec
    result comes back in a single device fetch: the standard
    sampler.pack_output layout ([B, K, 3+2W]) plus a trailing broadcast
    `accepted` column -> [B, K, 4+2W].  Unpacked by
    _HostSamplerOutput.from_packed on [..., :-1].  Called from INSIDE
    the jitted ragged_verify program (runner._build_ragged_verify_fn)."""
    from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod

    packed = sampler_mod.pack_output(sampler_mod.SamplerOutput(
        tokens=emitted, logprob=lp, rank=rank,
        topn_ids=topn_ids, topn_logprobs=topn_lp,
    ))
    acc = jnp.broadcast_to(
        accepted.astype(jnp.int32)[:, None, None], (*emitted.shape, 1)
    )
    return jnp.concatenate([packed, acc], axis=-1)


def spec_eligible(params) -> bool:  # noqa: ANN001
    """Row eligibility for speculative verify spans.

    Greedy rows verify by argmax match; unseeded sampled rows (any
    temperature, top-k/top-p) verify by rejection sampling — accept
    draft token d with prob min(1, p(d)/q(d)), resample the residual on
    reject — which preserves the target distribution exactly (Leviathan
    et al.; the mechanism the reference consumes from vLLM's spec
    decode).  Excluded (these rows ride a plain one-token decode span in
    the SAME ragged dispatch — eligibility is per row, not per batch):

    * knobs whose state evolves WITHIN a speculation window (repetition
      penalty's seen matrix, typical-p's entropy set, length-penalty/
      min-tokens EOS shaping, FSM masks);
    * SEEDED sampled requests: the sampler guarantees a seeded request
      replays the same draw stream no matter how it is batched
      (engine/sampler.py), and the spec path's salted draft/accept/emit
      streams differ from the fused sampler's — a seeded row must always
      take the one deterministic path.
    """
    return (
        params.repetition_penalty == 1.0
        and params.typical_p == 1.0
        and params.length_penalty is None
        and params.min_tokens == 0
        and params.structured_outputs is None
        and (params.temperature == 0.0 or params.seed is None)
    )


@dataclasses.dataclass
class SpecStats:
    proposed: int = 0
    accepted: int = 0
    dispatches: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


class SpeculativeDecoder:
    """Owns the draft model's device state + the propose program.

    Verification itself lives in the runner's jitted ``ragged_verify``
    entry point (the verify span IS part of the ragged dispatch); this
    class contributes the draft side: cache mirroring/catch-up, the
    γ-step propose scan, and acceptance accounting.
    """

    def __init__(
        self,
        runner: "ModelRunner",
        draft_model,  # noqa: ANN001
        draft_params,  # noqa: ANN001
        num_speculative_tokens: int,
    ):
        if num_speculative_tokens < 1:
            raise ValueError("--num-speculative-tokens must be >= 1")
        self.runner = runner
        self.gamma = num_speculative_tokens
        self.draft_model = draft_model
        self.stats = SpecStats()
        # time-decayed per-dispatch acceptance (30s half-life): the
        # responsive signal the γ auto-tuner consumes, exported as
        # spec_acceptance_rate_ewma next to the lifetime rate
        from vllm_tgis_adapter_tpu.telemetry.ewma import DecayedEwma

        self.acceptance_ewma = DecayedEwma(half_life_s=30.0)

        tcfg = runner.config.model_config
        dcfg = draft_model.config
        if dcfg.vocab_size != tcfg.vocab_size:
            raise ValueError(
                f"draft vocab_size {dcfg.vocab_size} != target "
                f"{tcfg.vocab_size}; speculative decoding requires a "
                "shared tokenizer"
            )

        mesh = runner.mesh
        draft_model.mesh = mesh
        cache_cfg = runner.config.cache_config
        cache_dtype = cache_cfg.cache_dtype
        if mesh is not None:
            from vllm_tgis_adapter_tpu.parallel import (
                cache_sharding,
                shard_llama_params,
                validate_tp_divisibility,
            )

            validate_tp_divisibility(dcfg, mesh.shape["tp"])
            draft_params = shard_llama_params(mesh, draft_params)
            sh = cache_sharding(mesh)
            out_sh = sh
            if cache_cfg.kv_quantization != "none":
                from jax.sharding import (
                    NamedSharding,
                    PartitionSpec as _P,
                )

                from vllm_tgis_adapter_tpu.ops.kv_quant import (
                    QuantizedKVCache,
                )

                out_sh = QuantizedKVCache(
                    sh,
                    NamedSharding(mesh, _P(None, "tp", None)),
                    cache_cfg.block_size,
                )
            self.draft_caches = jax.jit(
                lambda: draft_model.make_kv_caches(
                    runner.num_slots, cache_dtype,
                    quantization=cache_cfg.kv_quantization,
                    block_size=cache_cfg.block_size,
                ),
                out_shardings=(out_sh, out_sh),
            )()
        else:
            # the draft's paged cache follows the target's quantization
            # (greedy acceptance compares against TARGET logits, so a
            # quantized draft never perturbs emitted tokens)
            self.draft_caches = draft_model.make_kv_caches(
                runner.num_slots, cache_dtype,
                quantization=cache_cfg.kv_quantization,
                block_size=cache_cfg.block_size,
            )
        self.draft_params = draft_params

        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._draft_prefill_fn = jax.jit(
            draft_model.prefill, donate_argnums=donate
        )
        self._draft_chunk_fn = jax.jit(
            functools.partial(
                draft_model.prefill_chunk, block_size=runner.block_size
            ),
            donate_argnums=donate,
        )
        self._propose_fn = self._build_propose_fn()

    # ------------------------------------------------------------- prefill

    def draft_prefill(self, prep) -> None:  # noqa: ANN001
        """Mirror the target's (legacy solo) prefill chunk into the draft
        cache.  The ragged path never mirrors at prefill — verify-time
        catch-up (``catch_up``) replays whatever the draft is missing."""
        put = self.runner._put
        common = (
            self.draft_params,
            self.draft_caches,
            put(prep.token_ids),
            put(prep.positions),
            put(prep.slot_mapping),
            put(np.asarray(prep.t, np.int32)),
        )
        # logits for row 0 only — the draft's prefill output is unused,
        # only its KV writes matter
        idx = put(np.asarray([0], np.int32))
        if prep.start_pos == 0:
            _, self.draft_caches = self._draft_prefill_fn(*common, idx)
        else:
            _, self.draft_caches = self._draft_chunk_fn(
                *common, put(prep.block_table), idx
            )

    def catch_up(self, catchups: list[dict]) -> None:
        """Replay lagging rows' missing context through the draft (rows
        that decoded as plain spans, fresh prompts the ragged path
        prefilled target-only, prefix-cache / host-tier adopted spans
        the draft never saw).  Chunk widths ride the prefill-bucket pad
        ladder, so catch-up adds no compile shapes."""
        put = self.runner._put
        for cu in catchups:
            _, self.draft_caches = self._draft_chunk_fn(
                self.draft_params,
                self.draft_caches,
                put(cu["token_ids"]),
                put(cu["positions"]),
                put(cu["slot_mapping"]),
                put(np.asarray(cu["t"], np.int32)),
                put(cu["block_table"]),
                put(np.asarray([0], np.int32)),
            )

    # -------------------------------------------------------------- propose

    def _build_propose_fn(self):
        """One propose program for greedy AND sampled rows: the draft
        SAMPLES from its (temperature/top-k/top-p transformed)
        distribution — greedy rows degenerate to argmax through the
        one-hot ``_spec_dist`` — and returns that distribution per
        proposed position, which rejection-sampling verification needs
        to form the residual.  Inactive rows (non-spec spans sharing the
        dispatch) carry ``limits = -1`` so their writes drop."""
        draft = self.draft_model
        block_size = self.runner.block_size

        def propose(
            params, caches, tokens0, positions0, limits, block_tables,
            context_lens0, temps, top_k, top_p, base_key, gen0, gamma: int,
        ):
            max_blocks = block_tables.shape[1]

            def step(carry, k):
                caches, tok = carry
                pos = positions0 + k
                active = pos <= limits
                blk = jnp.take_along_axis(
                    block_tables,
                    jnp.clip(pos // block_size, 0, max_blocks - 1)[:, None],
                    axis=1,
                )[:, 0]
                slot = jnp.where(
                    active, blk * block_size + pos % block_size, -1
                )
                logits, caches = draft.decode(
                    params, caches, tok, pos, slot, block_tables,
                    context_lens0 + k, block_size,
                )
                probs = _spec_dist(logits, temps, top_k, top_p)
                keys = jax.vmap(
                    lambda s, p: jax.random.fold_in(
                        jax.random.fold_in(jax.random.PRNGKey(s), p),
                        _SALT_DRAFT,
                    )
                )(base_key, gen0 + k)
                sampled = jax.vmap(jax.random.categorical)(
                    keys, jnp.log(probs + 1e-30)
                )
                nxt = jnp.where(
                    temps <= 0.0, jnp.argmax(logits, axis=-1), sampled
                ).astype(jnp.int32)
                return (caches, nxt), (nxt, probs)

            # gamma+1 steps: the extra step feeds d_gamma back so ITS K/V
            # lands in the draft cache too — on a fully-accepted window
            # the next dispatch's context covers d_gamma's position, which
            # would otherwise be a permanent hole (its logits are unused)
            (caches, _), (drafted, qprobs) = jax.lax.scan(
                step, (caches, tokens0), jnp.arange(gamma + 1)
            )
            return caches, drafted[:gamma], qprobs[:gamma]  # [γ,B],[γ,B,V]

        donate = (1,) if jax.default_backend() == "tpu" else ()
        return jax.jit(propose, static_argnums=(12,), donate_argnums=donate)

    def propose(self, prep) -> tuple:  # noqa: ANN001
        """Run draft catch-up + the γ-step propose scan over a prepared
        ragged verify dispatch (runner.PreparedRagged spec fields).
        Returns device-resident ``(drafted [γ, S], q_probs [γ, S, V])``
        — enqueue-only, no host synchronisation."""
        self.catch_up(prep.draft_catchups)
        put = self.runner._put
        t = prep.tensors
        self.draft_caches, drafted, q_probs = self._propose_fn(
            self.draft_params,
            self.draft_caches,
            put(prep.spec_tokens0),
            put(prep.spec_positions0),
            put(prep.spec_limits),
            put(prep.block_tables),
            put(prep.spec_context0),
            put(np.asarray(t.temperature, np.float32)),
            put(np.asarray(t.top_k, np.int32)),
            put(np.asarray(t.top_p, np.float32)),
            put(np.asarray(t.base_key, np.uint32)),
            put(np.asarray(t.gen_len, np.int32)),
            self.gamma,
        )
        return drafted, q_probs

    # ----------------------------------------------------------- accounting

    def note_batch(self, proposed: int, accepted: int) -> None:
        """Fold one verify dispatch's acceptance into the stats + the
        spec metrics (called from the commit path with host counts)."""
        self.stats.proposed += proposed
        self.stats.accepted += accepted
        self.stats.dispatches += 1
        if proposed:
            self.acceptance_ewma.update(accepted / proposed)
        try:
            from vllm_tgis_adapter_tpu import metrics

            metrics.spec_proposed_tokens_total.inc(proposed)
            metrics.spec_accepted_tokens_total.inc(accepted)
        except Exception:  # pragma: no cover - metrics are best-effort
            pass
        if self.stats.dispatches % _LOG_EVERY == 0:
            logger.info(
                "speculative decoding: %.1f%% acceptance over %d proposed "
                "tokens (%d dispatches)",
                100 * self.stats.acceptance_rate, self.stats.proposed,
                self.stats.dispatches,
            )
