"""Request-level sampling parameters.

The engine-side analog of ``vllm.SamplingParams`` as consumed by the
reference adapter (grpc_server.py:606-622): temperature/top-k/top-p/seed,
typical-p and exponential length-penalty warpers, repetition penalty,
min/max tokens, stop sequences, logprob counts, and structured-output
constraints.  Validation here covers the cases vLLM itself would reject
(the TGIS-level validation lives in grpc/validation.py).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional


class RequestOutputKind(enum.Enum):
    # full accumulated output on every yield
    CUMULATIVE = 0
    # only the newly generated tokens since the last yield
    DELTA = 1
    # a single yield at request completion
    FINAL_ONLY = 2


@dataclasses.dataclass
class StructuredOutputsParams:
    """Constrained-decoding spec (reference: tgis_utils/structured_outputs.py)."""

    json: Optional[str] = None  # JSON schema string
    regex: Optional[str] = None
    choice: Optional[list[str]] = None
    grammar: Optional[str] = None
    json_object: bool = False

    def __post_init__(self) -> None:
        set_fields = [
            name
            for name in ("json", "regex", "choice", "grammar")
            if getattr(self, name)
        ] + (["json_object"] if self.json_object else [])
        if len(set_fields) != 1:
            raise ValueError(
                "exactly one structured-output mode must be set, got: "
                f"{set_fields or 'none'}"
            )


@dataclasses.dataclass
class SamplingParams:
    temperature: float = 1.0
    top_k: int = -1  # -1 disables
    top_p: float = 1.0
    typical_p: float = 1.0
    seed: Optional[int] = None
    max_tokens: Optional[int] = 16
    min_tokens: int = 0
    repetition_penalty: float = 1.0
    # (start_index, decay_factor) exponential EOS boost, TGIS-style
    length_penalty: Optional[tuple[int, float]] = None
    stop: Optional[list[str]] = None
    include_stop_str_in_output: bool = False
    skip_special_tokens: bool = True
    logprobs: Optional[int] = None
    prompt_logprobs: Optional[int] = None
    structured_outputs: Optional[StructuredOutputsParams] = None
    output_kind: RequestOutputKind = RequestOutputKind.CUMULATIVE
    # engine-internal: deadline propagated for metrics; servers enforce it
    ignore_eos: bool = False

    def __post_init__(self) -> None:  # noqa: C901
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be non-negative, got {self.temperature}"
            )
        if self.top_p <= 0.0 or self.top_p > 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.top_k < -1 or self.top_k == 0:
            raise ValueError(
                f"top_k must be -1 (disable) or at least 1, got {self.top_k}"
            )
        if not 0.0 < self.typical_p <= 1.0:
            raise ValueError(f"typical_p must be in (0, 1], got {self.typical_p}")
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError(f"max_tokens must be at least 1, got {self.max_tokens}")
        if self.min_tokens < 0:
            raise ValueError(
                f"min_tokens must be non-negative, got {self.min_tokens}"
            )
        if (
            self.max_tokens is not None
            and self.min_tokens > self.max_tokens
        ):
            raise ValueError(
                f"min_tokens must be <= max_tokens, got {self.min_tokens} > "
                f"{self.max_tokens}"
            )
        if not 0.0 < self.repetition_penalty <= 2.0:
            raise ValueError(
                "repetition_penalty must be in (0, 2], got "
                f"{self.repetition_penalty}"
            )
        if self.logprobs is not None and self.logprobs < 0:
            raise ValueError(f"logprobs must be non-negative, got {self.logprobs}")
        if self.seed is not None and not (0 <= self.seed < 2**64):
            raise ValueError(f"seed must fit in uint64, got {self.seed}")
        if self.stop:
            self.stop = [s for s in self.stop if s]

    @property
    def sampling_enabled(self) -> bool:
        return self.temperature > 0.0
