"""Paged device-resident LoRA adapter pool (S-LoRA-class serving).

The pre-pool path (``runner.sync_lora``) rebuilt the ENTIRE stacked
adapter tensor on the host and re-transferred it to the device on every
registry change, synchronously, in the step path — fine for 4 tenants,
fatal for a thousand (S-LoRA, arXiv:2311.03285; InfiniLoRA's
disaggregated variant).  This module replaces it with a paged pool:

* **Fixed-shape slot stacks.**  Device weights live in the same
  ``LoRAStacks`` layout the model already consumes (``a[target]:
  [L, S, d_in, max_rank]`` etc., S = ``max_loras`` + base slot 0), so
  ONE compiled program serves every adapter and a swap never retraces.
* **Async host→device streaming.**  A cold adapter's rank-padded
  per-layer blocks (``lora.build_adapter_blocks``) transfer and
  scatter into their slot via one jitted ``dynamic_update_slice``
  program — in a worker thread, overlapped with serving.  Never a
  full-stack rebuild, never on the event loop.  The update is
  deliberately NOT buffer-donated: a dispatch thread may have read the
  previous stacks reference concurrently, and consuming a donated
  (deleted) array there would poison the in-flight step; the price is
  one device-side stack copy per swap, fully off the host critical
  path.
* **LRU eviction over unpinned slots.**  Every in-flight sequence
  pins its adapter by name (registry refcounts, admission→finish), so
  eviction can only reassign slots no live row indexes.
* **Parking, not blocking.**  The scheduler's adapter gate
  (``Scheduler.lora_gate``) asks ``ensure_resident``; a miss issues
  the prefetch and the request PARKS in the waiting queue while
  resident-adapter work proceeds around it — batch composition prefers
  resident adapters, so churn cannot stall the step loop.

One pool per runner (per dp replica); the shared ``LoRAManager`` is
the host-RAM registry feeding every pool.  All pool state mutates on
the event-loop thread (or single-threaded in offline engines); worker
threads only build blocks and run device programs.
"""

from __future__ import annotations

import asyncio
import time
from typing import TYPE_CHECKING, Callable, Optional

import jax
import numpy as np

from vllm_tgis_adapter_tpu.compile_tracker import track_jit
from vllm_tgis_adapter_tpu.engine.lora import (
    LORA_TARGETS,
    LoRAStacks,
    _target_dims,
    build_adapter_blocks,
)
from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.utils import spawn_task

if TYPE_CHECKING:  # pragma: no cover - typing only
    from vllm_tgis_adapter_tpu.engine.arena import UnifiedArena
    from vllm_tgis_adapter_tpu.engine.lora import LoRAManager

logger = init_logger(__name__)


def _update_slot(stacks: LoRAStacks, slot, a_blocks, b_blocks, scale,
                 rank):  # noqa: ANN001
    """One adapter's blocks → its device slot (jitted once; ``slot``
    and ``rank`` are traced so every swap reuses the same program).
    ``rank`` is the adapter's rank BUCKET for the heterogeneous-rank
    gathered matmul; with gathering off (``stacks.ranks is None``, a
    static property of the pytree) it is carried but unused."""
    a = {
        t: stacks.a[t].at[:, slot].set(a_blocks[t]) for t in stacks.a
    }
    b = {
        t: stacks.b[t].at[:, slot].set(b_blocks[t]) for t in stacks.b
    }
    return LoRAStacks(
        a=a, b=b, scaling=stacks.scaling.at[slot].set(scale),
        ranks=(
            None if stacks.ranks is None
            else stacks.ranks.at[slot].set(rank)
        ),
    )


class AdapterPool:
    """Device residency of LoRA adapters for ONE runner."""

    def __init__(
        self,
        model_config,  # noqa: ANN001 — engine.config.ModelConfig
        max_loras: int,
        max_lora_rank: int,
        put_fn: Callable,
        prefetch_concurrency: int = 2,
        gathered: bool = True,
    ):
        self.mcfg = model_config
        self.max_loras = max_loras
        self.max_rank = max_lora_rank
        self._put = put_fn
        # heterogeneous-rank gathered matmul (docs/LORA.md): stacks
        # carry a per-slot rank-bucket operand the model dispatches on
        self.gathered = gathered
        # unified paged arena (engine/arena.py, set by the engine core):
        # device residency charges true-rank pages against the shared
        # KV+adapter block budget; None = pre-arena fixed-slot behavior
        self.arena: Optional["UnifiedArena"] = None
        # host→device block builds allowed in flight at once; the final
        # slot scatter is serialized by _stream_lock regardless
        self.prefetch_concurrency = max(1, prefetch_concurrency)
        # the registry feeding this pool; set by the owning engine and
        # re-pointed by adopt_lora_manager on dp sharing / rebuild
        self.manager: Optional["LoRAManager"] = None
        # runner hook: called with the new stacks object after every
        # committed slot update (runner.lora_stacks stays current)
        self.on_commit: Optional[Callable] = None
        # name -> slot for RESIDENT adapters (committed streams only)
        self._slots: dict[str, int] = {}
        self._free: list[int] = list(range(max_loras, 0, -1))
        # name -> last-touch monotonic over resident adapters (LRU)
        self._lru: dict[str, float] = {}
        # names with a stream in flight (slot allocated, not committed)
        self._streaming: dict[str, object] = {}
        # names invalidated (host-evicted) while streaming: their commit
        # must drop the slot instead of publishing it
        self._invalidated: set[str] = set()
        self._stream_lock = asyncio.Lock()
        self._sema: Optional[asyncio.Semaphore] = None
        self._closed = False
        # admission-time lookup accounting (lora_pool_hit_rate)
        self.hits = 0
        self.misses = 0
        self.swaps_in = 0
        self.swaps_out = 0
        self.resident_high_water = 0
        # None only after release() (supervisor rebuild teardown)
        self.stacks: Optional[LoRAStacks] = self._zero_stacks()
        self._update_fn = track_jit(
            "lora_slot_update",
            jax.jit(_update_slot),
            label=lambda args, kwargs: "slot",
        )

    # ------------------------------------------------------------ stacks

    def _zero_stacks(self) -> LoRAStacks:
        s_count = self.max_loras + 1
        layers = self.mcfg.num_layers
        a = {}
        b = {}
        for target in LORA_TARGETS:
            din, dout = _target_dims(self.mcfg, target)
            a[target] = self._put(
                np.zeros((layers, s_count, din, self.max_rank), np.float32)
            )
            b[target] = self._put(
                np.zeros((layers, s_count, self.max_rank, dout), np.float32)
            )
        return LoRAStacks(
            a=a, b=b, scaling=self._put(np.zeros(s_count, np.float32)),
            ranks=(
                self._put(np.zeros(s_count, np.int32))
                if self.gathered
                else None
            ),
        )

    def release(self) -> None:
        """Drop the device stacks (supervisor rebuild: the replacement
        engine's pool allocates its own, and two cannot coexist in a
        tight HBM budget).  In-flight streams commit into nothing."""
        self._closed = True
        self.stacks = None
        self._slots.clear()
        self._lru.clear()
        if self.arena is not None:
            # the dying pool's charges return to the budget (the
            # replacement engine's pool starts uncharged)
            self.arena.release_pool(self)

    def close(self) -> None:
        """Terminal shutdown: stop accepting prefetches and cancel any
        in-flight stream tasks (engine.stop())."""
        self._closed = True
        for task in list(self._streaming.values()):
            cancel = getattr(task, "cancel", None)
            if cancel is not None:
                cancel()
        if self.arena is not None:
            self.arena.release_pool(self)

    # --------------------------------------------------------- residency

    def resident(self, lora_name: Optional[str]) -> bool:
        """True when the adapter's weights are live in a device slot
        (the placement router's per-replica residency probe)."""
        return bool(lora_name) and lora_name in self._slots

    @property
    def num_resident(self) -> int:
        return len(self._slots)

    def resident_names(self) -> list[str]:
        """Committed residents — the arena's eviction candidate set."""
        return list(self._slots)

    def last_touch(self, lora_name: str) -> float:
        """Last-touch monotonic time of a resident adapter (the
        adapter side of the arena's unified LRU comparison)."""
        return self._lru.get(lora_name, 0.0)

    def evict_resident(self, lora_name: str) -> None:
        """Evict ONE named resident adapter (arena reclaim under KV or
        sibling-adapter pressure).  Host registry entry and pins are
        untouched — the adapter falls back to host-RAM residency and
        re-streams on next use; callers must never pass a pinned name
        (the arena filters through ``manager.pinned``)."""
        slot = self._slots.pop(lora_name, None)
        self._lru.pop(lora_name, None)
        if slot is None:
            return
        self._free.append(slot)
        self.swaps_out += 1
        self._count_swap("out")
        if self.arena is not None:
            self.arena.release_adapter(self, lora_name)

    def _charge(self, lora_name: str, weights) -> bool:  # noqa: ANN001
        """Reserve this adapter's true-rank page cost in the arena
        (no-op pre-arena).  False = budget exhausted by live work; the
        request parks exactly like a slot-pressure miss."""
        if self.arena is None:
            return True
        from vllm_tgis_adapter_tpu.engine.lora import adapter_page_cost

        return self.arena.charge_adapter(
            self, lora_name,
            adapter_page_cost(
                self.mcfg, weights.rank, self.max_rank,
                self.arena.kv_page_bytes,
            ),
        )

    def _uncharge(self, lora_name: str) -> None:
        if self.arena is not None:
            self.arena.release_adapter(self, lora_name)

    def note_lookup(self, lora_name: str, replica: int = 0) -> None:
        """Admission-time hit/miss accounting — counted ONCE per
        request (the schedule-time gate retries every step and would
        inflate both sides).  The gauge carries the replica label: at
        dp>1 each pool's local ratio is its own series, not a
        last-writer-wins scribble over a global."""
        if lora_name in self._slots:
            self.hits += 1
        else:
            self.misses += 1
        try:
            from vllm_tgis_adapter_tpu import metrics

            total = self.hits + self.misses
            if total:
                metrics.lora_pool_hit_rate.labels(
                    replica=str(replica)
                ).set(self.hits / total)
        except Exception:  # pragma: no cover — telemetry must not raise
            pass

    def ensure_resident(self, lora_name: str) -> Optional[int]:
        """The scheduler gate: the adapter's slot when resident (LRU
        touched), else None with a prefetch issued — the request parks
        and the stream overlaps serving.

        An adapter unknown to the registry resolves to slot 0 (base
        weights) — the legacy ``slot_of`` contract for unloaded names,
        so a racing host-evict degrades exactly like the old path
        instead of wedging the request."""
        slot = self._slots.get(lora_name)
        if slot is not None:
            self._lru[lora_name] = time.monotonic()
            return slot
        if self.manager is None:
            return 0
        if self.manager.get_weights(lora_name) is None:
            if self.manager.request_disk_restore(lora_name):
                # the adapter is spilled to the disk tier: PARK while
                # it restores disk→host (then host→device streams it —
                # the full promotion walk, docs/MEMORY.md)
                return None
            # debug, not warning: the gate retries this every schedule
            # attempt and the condition is the documented legacy
            # behavior, not a fault
            logger.debug(
                "request references unregistered adapter %r; serving "
                "base weights (legacy slot-0 semantics)", lora_name,
            )
            return 0
        if self.prefetch(lora_name):
            # offline/sync engines stream inline — the adapter may be
            # resident the moment prefetch returns
            slot = self._slots.get(lora_name)
            if slot is not None:
                self._lru[lora_name] = time.monotonic()
                return slot
        return None

    # --------------------------------------------------------- streaming

    def prefetch(self, lora_name: str) -> bool:
        """Begin (or observe) host→device streaming for one adapter.
        Returns True when already resident.  Idempotent; safe to call
        every schedule attempt."""
        if self._closed:
            return False
        if lora_name in self._slots:
            return True
        if lora_name in self._streaming:
            return False
        weights = (
            self.manager.get_weights(lora_name)
            if self.manager is not None
            else None
        )
        if weights is None:
            return False
        slot = self._allocate_slot()
        if slot is None:
            # every slot is pinned by live rows: the request stays
            # parked; the gate re-prefetches once a pin releases
            return False
        if not self._charge(lora_name, weights):
            # unified-arena budget exhausted by live KV + pinned
            # adapters: park, exactly like slot pressure — the gate
            # retries as work drains
            self._free.append(slot)
            return False
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is None:
            # offline/sync engine (tests, batch runs): stream inline —
            # there is no event loop to protect.  Same failure contract
            # as the async path: a failed stream returns its slot and
            # the request stays parked, never crashes the schedule.
            try:
                self._stream_blocking(lora_name, weights, slot)
            except Exception:
                logger.exception(
                    "adapter stream for %r failed; slot %d returned to "
                    "the pool", lora_name, slot,
                )
                if lora_name not in self._slots:
                    self._free.append(slot)
                    self._uncharge(lora_name)
                return False
            return True
        self._streaming[lora_name] = spawn_task(
            self._stream(lora_name, weights, slot),
            name=f"lora-stream-{lora_name}", loop=loop,
        )
        return False

    def _allocate_slot(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        # LRU eviction over UNPINNED residents only: a pinned adapter's
        # slot index is live in scheduled rows and must never change
        victim = None
        for name in sorted(self._lru, key=self._lru.get):
            if self.manager is not None and self.manager.pinned(name):
                continue
            victim = name
            break
        if victim is None:
            return None
        slot = self._slots.pop(victim)
        self._lru.pop(victim, None)
        self.swaps_out += 1
        self._count_swap("out")
        self._uncharge(victim)
        logger.info("adapter pool: evicting %s from slot %d", victim, slot)
        return slot

    def invalidate(self, lora_name: str) -> None:
        """The host registry dropped this adapter: free its slot (no
        live pins exist by the registry's eviction contract)."""
        if lora_name in self._streaming:
            self._invalidated.add(lora_name)
        slot = self._slots.pop(lora_name, None)
        self._lru.pop(lora_name, None)
        if slot is not None:
            self._free.append(slot)
            self.swaps_out += 1
            self._count_swap("out")
        if lora_name not in self._streaming:
            # a streaming name keeps its charge until its commit/abort
            # path settles it (the _invalidated flag routes it there)
            self._uncharge(lora_name)

    def _build_device_blocks(self, weights):  # noqa: ANN001
        """Worker-thread half: host block assembly + device transfer of
        ONE adapter (the only per-swap host→device traffic)."""
        a_blocks, b_blocks = build_adapter_blocks(
            self.mcfg, self.max_rank, weights
        )
        return (
            {t: self._put(v) for t, v in a_blocks.items()},
            {t: self._put(v) for t, v in b_blocks.items()},
        )

    def _apply(self, slot: int, a_dev, b_dev, scaling: float,
               rank: int):  # noqa: ANN001
        """Worker-thread half: scatter one adapter's device blocks into
        its slot.  One compiled program for every (adapter, slot) —
        the rank bucket is a traced operand, never a compile shape."""
        return self._update_fn(
            self.stacks,
            np.int32(slot),
            a_dev,
            b_dev,
            np.float32(scaling),
            np.int32(rank),
        )

    def _commit(self, lora_name: str, slot: int, new_stacks) -> None:  # noqa: ANN001
        if self._closed or lora_name in self._invalidated:
            self._invalidated.discard(lora_name)
            if not self._closed:
                self._free.append(slot)
                self._uncharge(lora_name)
            return
        self.stacks = new_stacks
        if self.on_commit is not None:
            self.on_commit(new_stacks)
        self._slots[lora_name] = slot
        self._lru[lora_name] = time.monotonic()
        self.swaps_in += 1
        self.resident_high_water = max(
            self.resident_high_water, len(self._slots)
        )
        self._count_swap("in")

    def _rank_bucket(self, weights) -> int:  # noqa: ANN001
        from vllm_tgis_adapter_tpu.engine.lora import rank_bucket

        return rank_bucket(weights.rank, self.max_rank)

    def _stream_blocking(self, lora_name: str, weights, slot: int) -> None:  # noqa: ANN001
        t0 = time.monotonic()
        a_dev, b_dev = self._build_device_blocks(weights)
        new_stacks = self._apply(
            slot, a_dev, b_dev, weights.scaling, self._rank_bucket(weights)
        )
        self._commit(lora_name, slot, new_stacks)
        self._observe_prefetch(time.monotonic() - t0)

    async def _stream(self, lora_name: str, weights, slot: int) -> None:  # noqa: ANN001
        t0 = time.monotonic()
        try:
            if self._sema is None:
                self._sema = asyncio.Semaphore(self.prefetch_concurrency)
            async with self._sema:
                a_dev, b_dev = await asyncio.to_thread(
                    self._build_device_blocks, weights
                )
            # the scatter reads self.stacks: serialize against sibling
            # streams so no update is built on a stale base and lost
            async with self._stream_lock:
                new_stacks = await asyncio.to_thread(
                    self._apply, slot, a_dev, b_dev, weights.scaling,
                    self._rank_bucket(weights),
                )
                self._commit(lora_name, slot, new_stacks)
            self._observe_prefetch(time.monotonic() - t0)
        except Exception:
            logger.exception(
                "adapter stream for %r failed; slot %d returned to the "
                "pool", lora_name, slot,
            )
            if not self._closed and lora_name not in self._slots:
                self._free.append(slot)
                self._uncharge(lora_name)
        finally:
            self._streaming.pop(lora_name, None)
            self._invalidated.discard(lora_name)

    # ------------------------------------------------------------ metrics

    @staticmethod
    def _count_swap(direction: str) -> None:
        try:
            from vllm_tgis_adapter_tpu import metrics

            metrics.lora_swap_total.labels(direction=direction).inc()
        except Exception:  # pragma: no cover — telemetry must not raise
            pass

    @staticmethod
    def _observe_prefetch(seconds: float) -> None:
        try:
            from vllm_tgis_adapter_tpu import metrics

            metrics.lora_prefetch_seconds.observe(seconds)
        except Exception:  # pragma: no cover — telemetry must not raise
            pass

    def debug_state(self) -> dict:
        """``adapter_pool`` section of the per-replica /debug/state."""
        total = self.hits + self.misses
        return {
            "max_loras": self.max_loras,
            "registered": (
                len(self.manager.lora_requests)
                if self.manager is not None
                else 0
            ),
            "resident": sorted(
                self._slots, key=self._slots.get
            ),
            "streaming": sorted(self._streaming),
            "free_slots": len(self._free),
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "swaps_in": self.swaps_in,
            "swaps_out": self.swaps_out,
            "resident_high_water": self.resident_high_water,
        }
