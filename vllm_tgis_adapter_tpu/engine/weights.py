"""Weight loading: HF safetensors checkpoints → model param pytrees.

The TPU-native analog of vLLM's weight loader consumed through engine boot
(reference capability surface, SURVEY.md §2.3 "engine lifecycle").  Reads
every ``*.safetensors`` shard in a model directory and maps HF parameter
names onto the pytree layout of models/llama.py, transposing projection
matrices to ``[in, out]`` orientation.

When a sharding function is provided (parallel/sharding.py), each tensor is
placed onto the device mesh as it is loaded so host memory never holds more
than one full tensor (required for 70B-class models on a v5e slice).
"""

from __future__ import annotations

from pathlib import Path
from typing import TYPE_CHECKING, Callable, Optional

import jax
import jax.numpy as jnp
from safetensors import safe_open

from vllm_tgis_adapter_tpu.logging import init_logger

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import ModelConfig

logger = init_logger(__name__)

PlaceFn = Callable[[str, jax.Array], jax.Array]


def _np_to_jnp(tensor, dtype) -> jax.Array:
    return jnp.asarray(tensor).astype(dtype)


class CheckpointIndex:
    """Lazy name→shard index over a directory of safetensors files.

    Tensors are read one at a time on demand so host memory never holds
    more than one full tensor alongside the (possibly sharded) params —
    required for 70B-class models whose full checkpoint exceeds host RAM
    headroom and whose unsharded weights exceed one chip's HBM.
    """

    def __init__(self, model_path: str):
        files = sorted(Path(model_path).glob("*.safetensors"))
        if not files:
            raise ValueError(f"no *.safetensors files found in {model_path}")
        self._by_name: dict[str, Path] = {}
        # one open handle per shard (mmap-backed, cheap) — reopening per
        # tensor would re-parse each multi-GB shard's header ~720 times
        # for a 70B checkpoint
        self._handles: dict[Path, object] = {}
        for file in files:
            # framework="flax" decodes bf16 natively (numpy cannot)
            f = safe_open(file, framework="flax")
            self._handles[file] = f
            for name in f.keys():  # noqa: SIM118
                self._by_name[name] = file
        self._taken: set[str] = set()

    def __contains__(self, name: str) -> bool:
        return name in self._by_name and name not in self._taken

    def pop(self, name: str) -> jax.Array:
        self._taken.add(name)
        return self._handles[self._by_name[name]].get_tensor(name)

    def remaining(self) -> list[str]:
        return [n for n in self._by_name if n not in self._taken]


def load_checkpoint_tensors(model_path: str) -> dict:
    """Eager {hf_name: array} across all shards (tests/small models)."""
    index = CheckpointIndex(model_path)
    return {name: index.pop(name) for name in index.remaining()}



def open_checkpoint_index(config: "ModelConfig", model_path: str):
    """CheckpointIndex, wrapped for int4 (AWQ/GPTQ) checkpoints so
    quantized projections surface as plain fp ``.weight`` tensors
    (engine/quantized.py dequant-on-load)."""
    raw = CheckpointIndex(model_path)
    method = getattr(config, "checkpoint_quant", None)
    if method:
        from vllm_tgis_adapter_tpu.engine.quantized import (
            Int4CheckpointIndex,
        )

        logger.info(
            "int4 %s checkpoint: dequantizing group-wise (group_size=%d) "
            "to %s at load", method,
            config.checkpoint_quant_group_size, config.dtype.__name__,
        )
        raw = Int4CheckpointIndex(
            raw, method=method,
            group_size=config.checkpoint_quant_group_size,
        )
    return raw


def load_llama_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """Build the LlamaForCausalLM param pytree from a HF checkpoint."""
    place = place or (lambda _name, x: x)
    dtype = config.dtype
    raw = open_checkpoint_index(config, model_path)
    # gemma lineage: HF's RMSNorm computes (1 + w) * x̂; folding the
    # offset into the stored weight once here keeps the runtime norm
    # the plain w * x̂ shared by the whole family
    norm_offset = getattr(config, "norm_weight_offset", 0.0)

    def take(name: str, transpose: bool = False) -> jax.Array:
        if name not in raw:
            raise ValueError(f"checkpoint is missing tensor {name!r}")
        x = _np_to_jnp(raw.pop(name), dtype)
        if transpose:
            x = x.T
        if norm_offset and name.endswith(("layernorm.weight",
                                          "norm.weight")):
            x = x + norm_offset
        return place(name, x)

    params: dict = {
        "embed": take("model.embed_tokens.weight"),
        "final_norm": take("model.norm.weight"),
        "layers": [],
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = take("lm_head.weight", transpose=True)
    elif "lm_head.weight" in raw:
        raw.pop("lm_head.weight")

    # quantization-aware KV scales (docs/QUANTIZATION.md "Calibrated
    # scales"): checkpoints calibrated for fp8/int8 KV caches ship
    # per-layer k_scale/v_scale tensors (scalar or per-kv-head).  They
    # are collected into [L, Hkv] floors the quantized page cache uses
    # as the page-scale floor instead of pure amax; the runner pops
    # them off the pytree before the params reach any jitted program.
    import numpy as _np

    k_floors = _np.zeros((config.num_layers, config.num_kv_heads),
                         _np.float32)
    v_floors = _np.zeros_like(k_floors)
    saw_floors = False
    for i in range(config.num_layers):
        for which, dst in (("k_scale", k_floors), ("v_scale", v_floors)):
            name = f"model.layers.{i}.self_attn.{which}"
            if name not in raw:
                continue
            saw_floors = True
            val = _np.asarray(raw.pop(name), _np.float32).reshape(-1)
            # scalar broadcasts over heads; per-head vectors map 1:1
            dst[i, :] = (
                val[0] if val.size == 1 else val[: config.num_kv_heads]
            )
    if saw_floors:
        logger.info(
            "checkpoint carries calibrated k_scale/v_scale tensors: "
            "quantized KV pages will floor their page scales at the "
            "calibrated values (--kv-quantization)"
        )
        params["kv_scale_floors"] = (k_floors, v_floors)

    for i in range(config.num_layers):
        prefix = f"model.layers.{i}"
        layer = {
            "input_norm": take(f"{prefix}.input_layernorm.weight"),
            "post_attn_norm": take(f"{prefix}.post_attention_layernorm.weight"),
            "wq": take(f"{prefix}.self_attn.q_proj.weight", transpose=True),
            "wk": take(f"{prefix}.self_attn.k_proj.weight", transpose=True),
            "wv": take(f"{prefix}.self_attn.v_proj.weight", transpose=True),
            "wo": take(f"{prefix}.self_attn.o_proj.weight", transpose=True),
        }
        if getattr(config, "qk_norm", False):
            # qwen3 per-head-dim q/k RMSNorms
            layer["q_norm"] = take(f"{prefix}.self_attn.q_norm.weight")
            layer["k_norm"] = take(f"{prefix}.self_attn.k_norm.weight")
        if config.num_experts > 0:
            # mixtral: per-expert FFNs stacked into [E, ...] tensors
            # (w1=gate, w3=up, w2=down in HF naming); the stacked arrays
            # get their final mesh placement from shard_llama_params
            moe = f"{prefix}.block_sparse_moe"
            layer["router"] = take(f"{moe}.gate.weight", transpose=True)

            def stack(which: str, transpose: bool) -> jax.Array:
                return jnp.stack([
                    take(f"{moe}.experts.{e}.{which}.weight",
                         transpose=transpose)
                    for e in range(config.num_experts)
                ])

            layer["experts_gate"] = stack("w1", True)
            layer["experts_up"] = stack("w3", True)
            layer["experts_down"] = stack("w2", True)
        else:
            layer["w_gate"] = take(f"{prefix}.mlp.gate_proj.weight",
                                   transpose=True)
            layer["w_up"] = take(f"{prefix}.mlp.up_proj.weight",
                                 transpose=True)
            layer["w_down"] = take(f"{prefix}.mlp.down_proj.weight",
                                   transpose=True)
        if config.attention_bias:
            layer["bq"] = take(f"{prefix}.self_attn.q_proj.bias")
            layer["bk"] = take(f"{prefix}.self_attn.k_proj.bias")
            layer["bv"] = take(f"{prefix}.self_attn.v_proj.bias")
        params["layers"].append(layer)

    ignored = [n for n in raw.remaining() if "rotary_emb" not in n]
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def load_opt_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """OPT checkpoint → the shared decoder param pytree.

    HF OPT names: ``model.decoder.layers.N.self_attn.{q,k,v,out}_proj``,
    ``fc1``/``fc2``, ``self_attn_layer_norm`` (pre-attention LN) and the
    confusingly-named per-layer ``final_layer_norm`` (pre-MLP LN), plus a
    decoder-level ``final_layer_norm`` and the offset-by-2
    ``embed_positions`` table.  Some exports drop the ``model.`` prefix;
    both spellings are accepted.
    """
    place = place or (lambda _name, x: x)
    dtype = config.dtype
    raw = open_checkpoint_index(config, model_path)

    def take(name: str, transpose: bool = False) -> jax.Array:
        for cand in (f"model.{name}", name):
            if cand in raw:
                x = _np_to_jnp(raw.pop(cand), dtype)
                if transpose:
                    x = x.T
                return place(cand, x)
        raise ValueError(f"checkpoint is missing tensor {name!r}")

    params: dict = {
        "embed": take("decoder.embed_tokens.weight"),
        "pos_embed": take("decoder.embed_positions.weight"),
        "final_norm": take("decoder.final_layer_norm.weight"),
        "final_norm_bias": take("decoder.final_layer_norm.bias"),
        "layers": [],
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = take("lm_head.weight", transpose=True)
    else:
        # tied exports often still materialise the duplicate tensor
        for cand in ("lm_head.weight", "model.lm_head.weight"):
            if cand in raw:
                raw.pop(cand)

    for i in range(config.num_layers):
        prefix = f"decoder.layers.{i}"
        layer = {
            "input_norm": take(f"{prefix}.self_attn_layer_norm.weight"),
            "input_norm_bias": take(f"{prefix}.self_attn_layer_norm.bias"),
            "post_attn_norm": take(f"{prefix}.final_layer_norm.weight"),
            "post_attn_norm_bias": take(f"{prefix}.final_layer_norm.bias"),
            "wq": take(f"{prefix}.self_attn.q_proj.weight", transpose=True),
            "wk": take(f"{prefix}.self_attn.k_proj.weight", transpose=True),
            "wv": take(f"{prefix}.self_attn.v_proj.weight", transpose=True),
            "wo": take(f"{prefix}.self_attn.out_proj.weight",
                       transpose=True),
            "w_up": take(f"{prefix}.fc1.weight", transpose=True),
            "w_down": take(f"{prefix}.fc2.weight", transpose=True),
        }
        if config.attention_bias:
            layer["bq"] = take(f"{prefix}.self_attn.q_proj.bias")
            layer["bk"] = take(f"{prefix}.self_attn.k_proj.bias")
            layer["bv"] = take(f"{prefix}.self_attn.v_proj.bias")
        if config.attention_out_bias:
            layer["bo"] = take(f"{prefix}.self_attn.out_proj.bias")
        if config.mlp_bias:
            layer["b_up"] = take(f"{prefix}.fc1.bias")
            layer["b_down"] = take(f"{prefix}.fc2.bias")
        params["layers"].append(layer)

    ignored = raw.remaining()
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def _make_take(raw, dtype, place, prefixes):
    """Tensor lookup over alternative name prefixes; ``placed=False``
    returns the raw host array (for tensors that are re-laid-out before
    placement, e.g. fused QKV)."""

    def take(name: str, transpose: bool = False, placed: bool = True):
        for pre in prefixes:
            cand = pre + name
            if cand in raw:
                x = _np_to_jnp(raw.pop(cand), dtype)
                if transpose:
                    x = x.T
                return place(cand, x) if placed else x
        raise ValueError(f"checkpoint is missing tensor {name!r}")

    return take


def _split_fused_qkv(take, place, prefix: str, h: int, dh: int, d: int,
                     *, bias: bool) -> dict:
    """De-interleave a head-major fused ``[H·3·Dh, d]`` query_key_value
    tensor (the HF gpt_neox AND bloom layout: each head's q, k, v rows
    adjacent) into per-projection ``[in, out]`` matrices, placed under
    q/k/v_proj alias names so the standard Megatron column-parallel
    specs apply (parallel/sharding.py suffix table)."""
    out = {}
    fused_w = take(
        f"{prefix}.query_key_value.weight", placed=False
    ).reshape(h, 3, dh, d)
    for j, proj in enumerate(("q", "k", "v")):
        out[f"w{proj}"] = place(
            f"{prefix}.{proj}_proj.weight",
            fused_w[:, j].reshape(h * dh, d).T,
        )
    if bias:
        fused_b = take(
            f"{prefix}.query_key_value.bias", placed=False
        ).reshape(h, 3, dh)
        for j, proj in enumerate(("q", "k", "v")):
            out[f"b{proj}"] = place(
                f"{prefix}.{proj}_proj.bias",
                fused_b[:, j].reshape(h * dh),
            )
    return out


def load_gpt_neox_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """GPT-NeoX / Pythia checkpoint → the shared decoder param pytree.

    The attention projection ships FUSED and head-interleaved:
    ``query_key_value.weight`` is ``[H·3·Dh, d]`` with each head's q, k,
    v rows adjacent.  De-interleave to per-projection matrices BEFORE
    mesh placement, so the split tensors land with the standard Megatron
    column-parallel specs (placed under q/k/v_proj alias names, matching
    parallel/sharding.py's suffix table).
    """
    place = place or (lambda _name, x: x)
    raw = open_checkpoint_index(config, model_path)
    h, dh, d = config.num_heads, config.head_dim, config.hidden_size
    take = _make_take(raw, config.dtype, place, ("",))

    params: dict = {
        "embed": take("gpt_neox.embed_in.weight"),
        "final_norm": take("gpt_neox.final_layer_norm.weight"),
        "final_norm_bias": take("gpt_neox.final_layer_norm.bias"),
        "layers": [],
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = take("embed_out.weight", transpose=True)

    for i in range(config.num_layers):
        prefix = f"gpt_neox.layers.{i}"
        layer = {
            "input_norm": take(f"{prefix}.input_layernorm.weight"),
            "input_norm_bias": take(f"{prefix}.input_layernorm.bias"),
            "post_attn_norm": take(
                f"{prefix}.post_attention_layernorm.weight"
            ),
            "post_attn_norm_bias": take(
                f"{prefix}.post_attention_layernorm.bias"
            ),
            "wo": take(f"{prefix}.attention.dense.weight", transpose=True),
            "bo": take(f"{prefix}.attention.dense.bias"),
            "w_up": take(f"{prefix}.mlp.dense_h_to_4h.weight",
                         transpose=True),
            "b_up": take(f"{prefix}.mlp.dense_h_to_4h.bias"),
            "w_down": take(f"{prefix}.mlp.dense_4h_to_h.weight",
                           transpose=True),
            "b_down": take(f"{prefix}.mlp.dense_4h_to_h.bias"),
        }
        layer |= _split_fused_qkv(
            take, place, f"{prefix}.attention", h, dh, d,
            bias=config.attention_bias,
        )
        params["layers"].append(layer)

    # attention.bias / masked_bias are HF's precomputed causal-mask
    # buffers, not weights
    ignored = [
        n for n in raw.remaining()
        if "rotary_emb" not in n
        and not n.endswith(("attention.bias", "attention.masked_bias"))
    ]
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def load_bloom_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """BLOOM checkpoint → the shared decoder param pytree.

    Layers live under ``h.{i}`` with the same fused head-interleaved
    ``query_key_value`` layout as GPT-NeoX (``[H·3·Dh, d]``, each head's
    q/k/v rows adjacent — HF BloomAttention._split_heads), de-interleaved
    before placement under q/k/v_proj alias names.  A LayerNorm sits
    directly on the embeddings (``word_embeddings_layernorm``); the head
    is tied.  Both bare and ``transformer.``-prefixed exports load.
    """
    place = place or (lambda _name, x: x)
    raw = open_checkpoint_index(config, model_path)
    h, dh, d = config.num_heads, config.head_dim, config.hidden_size
    take = _make_take(raw, config.dtype, place, ("", "transformer."))

    params: dict = {
        "embed": take("word_embeddings.weight"),
        "embed_norm": take("word_embeddings_layernorm.weight"),
        "embed_norm_bias": take("word_embeddings_layernorm.bias"),
        "final_norm": take("ln_f.weight"),
        "final_norm_bias": take("ln_f.bias"),
        "layers": [],
    }
    for cand in ("lm_head.weight",):  # tied; drop duplicate exports
        if cand in raw:
            raw.pop(cand)

    for i in range(config.num_layers):
        prefix = f"h.{i}"
        layer = {
            "input_norm": take(f"{prefix}.input_layernorm.weight"),
            "input_norm_bias": take(f"{prefix}.input_layernorm.bias"),
            "post_attn_norm": take(
                f"{prefix}.post_attention_layernorm.weight"
            ),
            "post_attn_norm_bias": take(
                f"{prefix}.post_attention_layernorm.bias"
            ),
            "wo": take(f"{prefix}.self_attention.dense.weight",
                       transpose=True),
            "bo": take(f"{prefix}.self_attention.dense.bias"),
            "w_up": take(f"{prefix}.mlp.dense_h_to_4h.weight",
                         transpose=True),
            "b_up": take(f"{prefix}.mlp.dense_h_to_4h.bias"),
            "w_down": take(f"{prefix}.mlp.dense_4h_to_h.weight",
                           transpose=True),
            "b_down": take(f"{prefix}.mlp.dense_4h_to_h.bias"),
        }
        layer |= _split_fused_qkv(
            take, place, f"{prefix}.self_attention", h, dh, d, bias=True,
        )
        params["layers"].append(layer)

    ignored = raw.remaining()
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def load_gpt2_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """GPT-2 checkpoint → the shared decoder param pytree.

    HF GPT-2 stores projections as Conv1D — already ``[in, out]``, so no
    transpose anywhere.  ``attn.c_attn.weight`` is the fused ``[d, 3d]``
    projection whose COLUMNS split into plain q|k|v thirds (heads are
    contiguous within each third, unlike the neox/bloom per-head
    interleave).  ``wpe`` is the learned position table (no offset);
    the head is tied to ``wte``.  Both bare and ``transformer.``-prefixed
    exports load.
    """
    place = place or (lambda _name, x: x)
    raw = open_checkpoint_index(config, model_path)
    d = config.hidden_size
    take = _make_take(raw, config.dtype, place, ("", "transformer."))

    params: dict = {
        "embed": take("wte.weight"),
        "pos_embed": take("wpe.weight"),
        "final_norm": take("ln_f.weight"),
        "final_norm_bias": take("ln_f.bias"),
        "layers": [],
    }
    for cand in ("lm_head.weight",):  # tied; drop duplicate exports
        if cand in raw:
            raw.pop(cand)

    for i in range(config.num_layers):
        prefix = f"h.{i}"
        fused_w = take(f"{prefix}.attn.c_attn.weight", placed=False)
        fused_b = take(f"{prefix}.attn.c_attn.bias", placed=False)
        layer = {
            "input_norm": take(f"{prefix}.ln_1.weight"),
            "input_norm_bias": take(f"{prefix}.ln_1.bias"),
            "post_attn_norm": take(f"{prefix}.ln_2.weight"),
            "post_attn_norm_bias": take(f"{prefix}.ln_2.bias"),
            "wo": take(f"{prefix}.attn.c_proj.weight"),
            "bo": take(f"{prefix}.attn.c_proj.bias"),
            "w_up": take(f"{prefix}.mlp.c_fc.weight"),
            "b_up": take(f"{prefix}.mlp.c_fc.bias"),
            "w_down": take(f"{prefix}.mlp.c_proj.weight"),
            "b_down": take(f"{prefix}.mlp.c_proj.bias"),
        }
        for j, proj in enumerate(("q", "k", "v")):
            layer[f"w{proj}"] = place(
                f"{prefix}.{proj}_proj.weight",
                fused_w[:, j * d:(j + 1) * d],
            )
            layer[f"b{proj}"] = place(
                f"{prefix}.{proj}_proj.bias",
                fused_b[j * d:(j + 1) * d],
            )
        params["layers"].append(layer)

    ignored = [n for n in raw.remaining()
               if not n.endswith(("attn.bias", "attn.masked_bias"))]
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def load_phi3_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """Phi-3 checkpoint → the shared decoder param pytree.

    Llama block chemistry with two FUSED projections: ``qkv_proj`` is
    ``[(H+2·Hkv)·Dh, d]`` with q, k, v stacked as contiguous ROW slices
    (not per-head interleaved like neox/bloom), and ``gate_up_proj`` is
    ``[2f, d]`` with gate on top of up.  Both split before placement so
    the standard Megatron column-parallel specs apply.
    """
    place = place or (lambda _name, x: x)
    raw = open_checkpoint_index(config, model_path)
    h, hkv, dh = config.num_heads, config.num_kv_heads, config.head_dim
    f = config.intermediate_size
    take = _make_take(raw, config.dtype, place, ("",))

    params: dict = {
        "embed": take("model.embed_tokens.weight"),
        "final_norm": take("model.norm.weight"),
        "layers": [],
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = take("lm_head.weight", transpose=True)
    elif "lm_head.weight" in raw:
        raw.pop("lm_head.weight")

    for i in range(config.num_layers):
        prefix = f"model.layers.{i}"
        fused_qkv = take(f"{prefix}.self_attn.qkv_proj.weight",
                         placed=False)
        fused_gu = take(f"{prefix}.mlp.gate_up_proj.weight", placed=False)
        q_rows, kv_rows = h * dh, hkv * dh
        layer = {
            "input_norm": take(f"{prefix}.input_layernorm.weight"),
            "post_attn_norm": take(
                f"{prefix}.post_attention_layernorm.weight"
            ),
            "wq": place(f"{prefix}.self_attn.q_proj.weight",
                        fused_qkv[:q_rows].T),
            "wk": place(f"{prefix}.self_attn.k_proj.weight",
                        fused_qkv[q_rows : q_rows + kv_rows].T),
            "wv": place(f"{prefix}.self_attn.v_proj.weight",
                        fused_qkv[q_rows + kv_rows :].T),
            "wo": take(f"{prefix}.self_attn.o_proj.weight",
                       transpose=True),
            "w_gate": place(f"{prefix}.mlp.gate_proj.weight",
                            fused_gu[:f].T),
            "w_up": place(f"{prefix}.mlp.up_proj.weight", fused_gu[f:].T),
            "w_down": take(f"{prefix}.mlp.down_proj.weight",
                           transpose=True),
        }
        params["layers"].append(layer)

    ignored = [n for n in raw.remaining() if "rotary_emb" not in n]
    if ignored:
        logger.warning("ignored %d unexpected checkpoint tensors: %s",
                       len(ignored), ignored[:5])
    return params


def load_model_params(
    config: "ModelConfig",
    model_path: str,
    place: Optional[PlaceFn] = None,
) -> dict:
    """Dispatch to the checkpoint layout for ``config.model_type``."""
    if config.model_type == "opt":
        return load_opt_params(config, model_path, place)
    if config.model_type == "gpt_neox":
        return load_gpt_neox_params(config, model_path, place)
    if config.model_type == "bloom":
        return load_bloom_params(config, model_path, place)
    if config.model_type == "gpt2":
        return load_gpt2_params(config, model_path, place)
    if config.model_type == "phi3":
        return load_phi3_params(config, model_path, place)
    return load_llama_params(config, model_path, place)


# ------------------------------------------------------------- quantization

# Per-layer 2-D projection weights eligible for weight-only int8 (the
# decode-phase HBM bandwidth dominators).  Embeddings, lm_head, norms,
# biases and the mixtral expert stacks stay in the model dtype: the first
# two feed gather/logits numerics, the rest are small.
INT8_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _quantize_int8(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-output-channel int8: w ≈ q8 · scale[out].

    The scale factors out of the contraction over the in dim, so
    ``(x @ q8) * scale`` reproduces ``x @ w`` exactly up to the rounding
    step — the standard weight-only scheme the reference gets from
    vLLM's quantization engine (consumed via
    /root/reference/src/vllm_tgis_adapter/tgis_utils/args.py:127-136).
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_params_int8(params: dict) -> dict:
    """Replace eligible projection leaves with ``{key}_q8`` + ``{key}_scale``
    pairs (models/llama.py ``linear`` consumes either representation).

    Runs after (possibly sharded) load: each int8 leaf keeps its source
    weight's mesh placement, and the [out] scale vector takes the
    weight's out-axis spec, so Megatron TP semantics are unchanged.
    Memory drops ~2× (bf16) / ~4× (f32) for the quantized leaves, and
    the KV-pool auto-sizing (kv_cache.resolve_num_blocks) sees the
    savings because it reads free HBM after weights are resident.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    for layer in params.get("layers", []):
        for key in INT8_QUANT_KEYS:
            w = layer.pop(key, None)
            if w is None:
                continue
            q, scale = _quantize_int8(w)
            sh = getattr(w, "sharding", None)
            if isinstance(sh, NamedSharding):
                q = jax.device_put(q, sh)
                out_axis = sh.spec[1] if len(sh.spec) > 1 else None
                scale = jax.device_put(
                    scale, NamedSharding(sh.mesh, PartitionSpec(out_axis))
                )
            layer[key + "_q8"] = q
            layer[key + "_scale"] = scale
    return params
