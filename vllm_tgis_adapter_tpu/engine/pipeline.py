"""Pipeline-parallel model runner: layer stages over disjoint device groups.

The reference stack's engine tier supports ``--pipeline-parallel-size``
(vLLM arg surface consumed via the adapter's parser, SURVEY.md §2.3/§2.4);
this is the TPU-native equivalent.  The model's layers are split into S
contiguous stages, each owning a disjoint ``tp``-sized device slice with
its own layer-sliced KV cache and jitted stage program; activations hop
stage to stage with ``jax.device_put`` (ICI transfers on real hardware).
PP's primary inference value is CAPACITY — serving a model S× bigger than
one device group's HBM.  Decode additionally overlaps the stages: the
batch splits into up to S microbatches whose chains are issued with no
host synchronisation (sampled tokens feed back to stage 0 as device
arrays), so JAX's async dispatch keeps every stage busy on a different
microbatch.  Prefill chains remain sequential per prompt (single-request
latency pays the stage bubble there).

Scope (fail-fast otherwise, engine/config.py validation): composes with
TP (stage meshes), DP (one pipeline per replica), LoRA (stage-sliced
adapter stacks), and everything sampler-side (guided decoding, seeded
sampling, penalties, stop strings, chunked prefill, prefix caching);
NOT with speculative decoding or sequence parallelism yet.

Decode under PP runs one step per stage chain (the single-jit fused
K-step scan cannot span device groups); the scheduler's
``num_decode_steps`` still batches K steps per plan, paid as K chained
dispatches.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

from vllm_tgis_adapter_tpu.compile_tracker import track_jit
from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod
from vllm_tgis_adapter_tpu.engine.runner import (
    ModelRunner,
    PromptLogprobInfo,
    SampledToken,
    _HostSamplerOutput,
)
from vllm_tgis_adapter_tpu.logging import init_logger

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import EngineConfig

logger = init_logger(__name__)


def _stage_meshes(config: "EngineConfig", devices=None) -> list:
    """The deterministic stage → device-slice mapping (shared by the
    weight-loading place fn and the runner so both land tensors on the
    same devices)."""
    from vllm_tgis_adapter_tpu.parallel import build_mesh

    pcfg = config.parallel_config
    pp, tp = pcfg.pipeline_parallel_size, pcfg.tensor_parallel_size
    devices = list(devices if devices is not None else jax.devices())
    if pp * tp > len(devices):
        raise ValueError(
            f"pipeline_parallel_size={pp} × tensor_parallel_size={tp} "
            f"needs {pp * tp} devices but only {len(devices)} are visible"
        )
    return [
        build_mesh(tensor_parallel_size=tp,
                   devices=devices[s * tp:(s + 1) * tp])
        for s in range(pp)
    ]


def make_pp_place_fn(config: "EngineConfig", devices=None):
    """Shard-on-load placement routed by pipeline stage: each layer's
    tensors go straight to their stage's device group (with the usual
    Megatron tp spec within it), embeddings to stage 0, head/final norm
    to the last — so no device group ever materialises another stage's
    weights."""
    from jax.sharding import NamedSharding

    from vllm_tgis_adapter_tpu.parallel.sharding import hf_name_spec

    meshes = _stage_meshes(config, devices)
    ranges = split_layer_ranges(
        config.model_config.num_layers, len(meshes)
    )

    def stage_of_layer(j: int) -> int:
        for s, (lo, hi) in enumerate(ranges):
            if lo <= j < hi:
                return s
        raise ValueError(f"layer index {j} out of range {ranges}")

    def place(name: str, x: jax.Array) -> jax.Array:
        # llama/opt/neox spell layers "…layers.N."; bloom uses "h.N."
        m = re.search(r"(?:^|\.)(?:layers|h)\.(\d+)\.", name)
        if m is not None:
            mesh = meshes[stage_of_layer(int(m.group(1)))]
        elif any(k in name for k in
                 ("embed_tokens", "embed_in", "embed_positions",
                  "word_embeddings", "wte", "wpe")):
            # word_embeddings also catches bloom's
            # word_embeddings_layernorm; wte/wpe are gpt2's token and
            # learned-position embeddings — all live on stage 0
            mesh = meshes[0]
        else:  # lm_head / embed_out / decoder-level final norm
            mesh = meshes[-1]
        return jax.device_put(x, NamedSharding(mesh, hf_name_spec(name)))

    return place


def split_layer_ranges(num_layers: int, stages: int) -> list[tuple[int, int]]:
    """Contiguous near-even layer ranges, earlier stages taking the
    remainder (they also hold the embedding)."""
    base, rem = divmod(num_layers, stages)
    ranges = []
    start = 0
    for s in range(stages):
        n = base + (1 if s < rem else 0)
        ranges.append((start, start + n))
        start += n
    return ranges


def split_pipeline_params(params: dict, ranges) -> list[dict]:
    """Stage param dicts (views, no copies): embed(+pos) on stage 0,
    final norm + lm_head on the last, each stage its layer slice."""
    stages = []
    last = len(ranges) - 1
    for s, (lo, hi) in enumerate(ranges):
        p: dict = {"layers": params["layers"][lo:hi]}
        if s == 0:
            p["embed"] = params["embed"]
            for name in ("pos_embed", "embed_norm", "embed_norm_bias"):
                if name in params:
                    p[name] = params[name]
        if s == last:
            # tied lm_head reads params["embed"]; the last stage needs its
            # own reference even when stage 0 also holds it
            if "embed" not in p:
                p["embed"] = params["embed"]
            p["final_norm"] = params["final_norm"]
            if "final_norm_bias" in params:
                p["final_norm_bias"] = params["final_norm_bias"]
            if "lm_head" in params:
                p["lm_head"] = params["lm_head"]
        stages.append(p)
    return stages


def _stage_decode(model, block_size, first, last,
                  params, caches, token_ids, step_ints, block_tables,
                  hidden=None, lora=None, lora_idx=None):
    """Jitted per-stage decode wrapper: the three identical per-step row
    vectors (positions, slot_mapping, context_lens) travel as ONE packed
    [3, B] int32 buffer per stage — each host↔device buffer is its own
    transfer (and, tunnel-attached, its own network round trip)."""
    return model.decode(
        params, caches, token_ids, step_ints[0], step_ints[1],
        block_tables, step_ints[2], block_size, lora, lora_idx,
        hidden=hidden, first_stage=first, last_stage=last,
    )


@dataclasses.dataclass
class _Stage:
    model: object  # layer-sliced model instance (own config/layer_offset)
    params: dict
    caches: tuple
    mesh: object  # this stage's tp mesh (placement + Megatron specs)
    data_sharding: object  # replicated NamedSharding on this stage's mesh
    first: bool
    last: bool
    prefill_fn: object
    chunk_fn: object
    decode_fn: object


class PipelineRunner(ModelRunner):
    """Drop-in ModelRunner with the device tier split into pp stages.

    Reuses the host halves (prepare_prefill / prepare_decode) unchanged;
    only initialisation and the execute halves differ.
    """

    def __init__(self, config: "EngineConfig", model, params, devices=None):
        from vllm_tgis_adapter_tpu.parallel import (
            cache_sharding,
            data_sharding,
            shard_llama_params,
            validate_tp_divisibility,
        )

        pcfg = config.parallel_config
        pp = pcfg.pipeline_parallel_size
        tp = pcfg.tensor_parallel_size
        mcfg = config.model_config
        cache_cfg = config.cache_config

        # same deterministic stage -> device-slice mapping as the weight
        # loader's place fn, so stage programs run where the weights live
        meshes = _stage_meshes(config, devices)
        if pp > mcfg.num_layers:
            raise ValueError(
                f"pipeline_parallel_size={pp} exceeds num_layers="
                f"{mcfg.num_layers}"
            )
        validate_tp_divisibility(mcfg, tp)

        # ---- host-side state the inherited prepare_* halves consume ----
        self.config = config
        self.model = model  # whole-model reference (config introspection)
        # calibrated kv-scale floors are a flat-runner feature
        # (--kv-quantization refuses pp>1); drop the sidecar so stage
        # slicing never sees a non-layer params key
        if isinstance(params, dict):
            params.pop("kv_scale_floors", None)
        self.block_size = cache_cfg.block_size
        self.num_slots = cache_cfg.num_blocks * cache_cfg.block_size
        self.max_blocks_per_seq = -(-mcfg.max_model_len // self.block_size)
        self._rng = np.random.default_rng(config.seed)
        self.lora_stacks = None
        self._stage_lora = None
        self._lora_version = 0
        self._seen_pad_lens = sorted(
            set(config.scheduler_config.prefill_buckets)
        )
        self.spec = None
        self.mesh = None  # whole-runner mesh is meaningless under pp

        # ---- stage construction ----
        self.ranges = split_layer_ranges(mcfg.num_layers, pp)
        stage_params = split_pipeline_params(params, self.ranges)
        model_cls = type(model)
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self.stages: list[_Stage] = []
        for s, (lo, hi) in enumerate(self.ranges):
            smesh = meshes[s]
            scfg = dataclasses.replace(mcfg, num_layers=hi - lo)
            smodel = model_cls(scfg)
            smodel.mesh = smesh
            smodel.layer_offset = lo
            sparams = shard_llama_params(smesh, stage_params[s])
            sh = cache_sharding(smesh)
            caches = jax.jit(
                lambda m=smodel: m.make_kv_caches(
                    self.num_slots, cache_cfg.cache_dtype
                ),
                out_shardings=(sh, sh),
            )()
            first, last = s == 0, s == pp - 1
            self.stages.append(_Stage(
                model=smodel,
                params=sparams,
                caches=caches,
                mesh=smesh,
                data_sharding=data_sharding(smesh),
                first=first,
                last=last,
                # stage fns are invoked with token_ids as a KEYWORD
                # (execute paths build a kwargs dict), so the shape
                # labels read kwargs, not positional args
                prefill_fn=track_jit(
                    f"pp{s}_prefill",
                    jax.jit(
                        functools.partial(
                            smodel.prefill, first_stage=first,
                            last_stage=last,
                        ),
                        donate_argnums=donate,
                    ),
                    label=lambda args, kwargs:
                        f"tokens={kwargs['token_ids'].shape[0]}",
                ),
                chunk_fn=track_jit(
                    f"pp{s}_prefill_chunk",
                    jax.jit(
                        functools.partial(
                            smodel.prefill_chunk,
                            block_size=self.block_size,
                            first_stage=first, last_stage=last,
                        ),
                        donate_argnums=donate,
                    ),
                    label=lambda args, kwargs:
                        f"tokens={kwargs['token_ids'].shape[0]}",
                ),
                decode_fn=track_jit(
                    f"pp{s}_decode",
                    jax.jit(
                        functools.partial(
                            _stage_decode, smodel, self.block_size,
                            first, last,
                        ),
                        donate_argnums=donate,
                    ),
                    label=lambda args, kwargs:
                        f"batch={kwargs['token_ids'].shape[-1]}",
                ),
            ))
        logger.info(
            "pipeline runner: %d stages × tp=%d, layer ranges %s",
            pp, tp, self.ranges,
        )

        last_stage = self.stages[-1]
        self._data_sharding = last_stage.data_sharding  # sampler inputs
        max_seqs = config.scheduler_config.max_num_seqs
        self.seen = self._put(jnp.zeros((max_seqs, mcfg.vocab_size), bool))

    # ------------------------------------------------------------- helpers

    def _stage_put(self, stage: _Stage, x):
        return jax.device_put(np.asarray(x), stage.data_sharding)

    def _place_lora_stacks(self, stacks):  # noqa: ANN001
        """Per-stage adapter stacks: the [L, ...] target arrays slice on
        the layer axis exactly like the params, so each stage's model
        indexes them with its LOCAL layer number.  Returns a bare truthy
        marker — keeping the full host stacks alive would pin gigabytes
        for big models; the sliced device copies hold the data."""
        self._stage_lora = []
        for stage, (lo, hi) in zip(self.stages, self.ranges):
            sliced = dataclasses.replace(
                stacks,
                a={t: v[lo:hi] for t, v in stacks.a.items()},
                b={t: v[lo:hi] for t, v in stacks.b.items()},
            )
            self._stage_lora.append(jax.tree.map(
                lambda x, st=stage: jax.device_put(
                    np.asarray(x), st.data_sharding
                ),
                sliced,
            ))
        return True

    # ------------------------------------------------------------- prefill

    # staged execution synchronises hidden-state handoffs between stage
    # device groups, so the enqueue-only dispatch/wait split does not
    # apply: dispatch returns the sentinel and wait runs the full staged
    # execution (engine/runner.py SYNC_DISPATCH contract)
    def dispatch_prefill(self, prep):
        from vllm_tgis_adapter_tpu.engine.runner import SYNC_DISPATCH

        return SYNC_DISPATCH

    def wait_prefill(self, prep, handle):
        return self.execute_prefill(prep)

    def dispatch_decode(self, prep):
        from vllm_tgis_adapter_tpu.engine.runner import SYNC_DISPATCH

        return SYNC_DISPATCH

    def wait_decode(self, prep, handle):
        return self.execute_decode(prep)

    def execute_prefill(self, prep):
        """Chain the prompt (chunk) through the stages; sample on the
        last stage's devices."""
        t = prep.t
        hidden = None
        logits = None
        for si, stage in enumerate(self.stages):
            common = dict(
                token_ids=self._stage_put(stage, prep.token_ids),
                positions=self._stage_put(stage, prep.positions),
                slot_mapping=self._stage_put(stage, prep.slot_mapping),
                valid_len=self._stage_put(stage, np.asarray(t, np.int32)),
                logits_indices=self._stage_put(stage, prep.logits_indices),
            )
            if self.lora_stacks is not None:
                common["lora"] = self._stage_lora[si]
                common["lora_slot"] = self._stage_put(
                    stage, np.asarray(prep.lora_slot, np.int32)
                )
            if not stage.first:
                common["hidden"] = jax.device_put(
                    hidden, stage.data_sharding
                )
            if prep.start_pos == 0:
                out, stage.caches = stage.prefill_fn(
                    stage.params, stage.caches, **common
                )
            else:
                out, stage.caches = stage.chunk_fn(
                    stage.params, stage.caches,
                    block_table=self._stage_put(stage, prep.block_table),
                    **common,
                )
            if stage.last:
                logits = out
            else:
                hidden = out
        prompt_info = None
        if prep.want_prompt_lp:
            prompt_info = PromptLogprobInfo.from_packed(
                sampler_mod.pack_prompt_logprob_parts(
                    sampler_mod.prompt_logprob_info(
                        logits, jnp.asarray(prep.lp_targets)
                    )
                ),
                prep.lp_rows,
            )
        if not prep.is_final:
            return None, prompt_info  # lp chunks carry their table rows

        if prep.want_prompt_lp:
            last_logits = logits[t - 1][None]
        else:
            last_logits = logits

        self.seen = sampler_mod.set_seen_row(
            self.seen,
            self._put(np.asarray(prep.row_slot)),
            self._put(prep.seen_tokens),
        )
        allowed_mask = (
            self._put(prep.allowed_row[None, :])
            if prep.allowed_row is not None
            else None
        )
        seen_rows = jnp.take(
            self.seen,
            jnp.clip(jnp.asarray([prep.row_slot]), 0, None),
            axis=0,
        )
        out = sampler_mod.sample(
            last_logits,
            seen_rows,
            jax.tree.map(self._put, prep.tensors),
            allowed_mask=allowed_mask,
        )
        self.seen = sampler_mod.update_seen(
            self.seen, jnp.asarray([prep.row_slot]), out.tokens
        )
        host = _HostSamplerOutput.from_packed(
            sampler_mod.pack_output(out)[None]
        )
        return host.token(0, 0), prompt_info

    # -------------------------------------------------------------- decode

    def execute_decode(self, prep) -> list[list[SampledToken]]:
        """K single-step stage chains per plan (the fused on-device scan
        cannot span device groups); penalties/sampling run on the last
        stage exactly as the fused path does.

        Overlap: the batch splits into up to ``num_stages`` microbatches
        and dispatches are issued STEP-MAJOR (all chains' step k before
        any chain's step k+1) with no host synchronisation — the sampled
        tokens feed back to stage 0 as device arrays.  Per-device queues
        execute FIFO, so step-major order is what lets stage s run
        microbatch m's step while stage s+1 runs m-1's (chain-major
        order would park a feedback-blocked dispatch at the head of the
        queue and serialise everything behind it).  The host blocks only
        once, collecting all K results at the end.  Microbatches touch
        disjoint seen-matrix rows, so their sampler calls' shared
        ordering on the last stage's device is not a correctness
        constraint."""
        b = prep.token_ids.shape[0]
        n_stages = len(self.stages)
        m_count = n_stages if (b % n_stages == 0 and b >= n_stages) else 1
        mb = b // m_count
        active_rows = np.asarray(prep.slots) >= 0
        rows_all = np.clip(np.asarray(prep.slots), 0, None)

        positions0 = np.asarray(prep.positions)
        limits = np.asarray(prep.limits)
        ctx0 = np.asarray(prep.context_lens)
        tables_host = np.asarray(prep.block_tables)

        # per-microbatch issue state; tensors leaves are [B] host numpy
        # (engine/sampler.py SamplingTensors.from_params keeps them on
        # host precisely so callers control the transfer)
        chains = []
        for m in range(m_count):
            lo, hi = m * mb, (m + 1) * mb
            chains.append(dict(
                lo=lo, hi=hi,
                tokens=None,  # device array after step 0
                tensors=jax.tree.map(
                    lambda x, lo=lo, hi=hi: self._put(x[lo:hi]),
                    prep.tensors,
                ),
                allowed=(
                    self._put(prep.allowed_mask[lo:hi])
                    if prep.allowed_mask is not None
                    else None
                ),
                rows=jnp.asarray(rows_all[lo:hi]),
                # stage-constant placements, done once per chain: block
                # tables plus a token placeholder for non-first stages
                # (decode() reads `hidden` there, not token_ids)
                tables=[
                    self._stage_put(stage, prep.block_tables[lo:hi])
                    for stage in self.stages
                ],
                tok_placeholder=[
                    self._stage_put(stage, prep.token_ids[lo:hi])
                    for stage in self.stages
                ],
                lora_idx=(
                    [
                        self._stage_put(stage, prep.lora_idx[lo:hi])
                        for stage in self.stages
                    ]
                    if prep.lora_idx is not None
                    else None
                ),
                outs=[],
            ))

        for k in range(prep.num_steps):
            for chain in chains:
                lo, hi = chain["lo"], chain["hi"]
                positions = positions0[lo:hi] + k
                active = (positions <= limits[lo:hi]) & active_rows[lo:hi]
                blk = np.take_along_axis(
                    tables_host[lo:hi],
                    np.clip(positions // self.block_size, 0,
                            self.max_blocks_per_seq - 1)[:, None],
                    axis=1,
                )[:, 0]
                slot = np.where(
                    active,
                    blk * self.block_size + positions % self.block_size,
                    -1,
                ).astype(np.int32)
                context_lens = (ctx0[lo:hi] + k).astype(np.int32)
                step_ints = np.stack([positions, slot, context_lens])

                hidden = None
                logits = None
                for si, stage in enumerate(self.stages):
                    if stage.first and chain["tokens"] is not None:
                        # sampled on the last stage, consumed on the
                        # first: device-to-device, no host sync
                        tok_in = jax.device_put(
                            chain["tokens"], stage.data_sharding
                        )
                    else:
                        tok_in = chain["tok_placeholder"][si]
                    kwargs = dict(
                        token_ids=tok_in,
                        step_ints=self._stage_put(stage, step_ints),
                        block_tables=chain["tables"][si],
                    )
                    if chain["lora_idx"] is not None:
                        kwargs["lora"] = self._stage_lora[si]
                        kwargs["lora_idx"] = chain["lora_idx"][si]
                    if not stage.first:
                        kwargs["hidden"] = jax.device_put(
                            hidden, stage.data_sharding
                        )
                    out, stage.caches = stage.decode_fn(
                        stage.params, stage.caches, **kwargs
                    )
                    if stage.last:
                        logits = out
                    else:
                        hidden = out

                t_k = dataclasses.replace(
                    chain["tensors"],
                    gen_len=chain["tensors"].gen_len + k,
                )
                seen_rows = jnp.take(self.seen, chain["rows"], axis=0)
                out = sampler_mod.sample(
                    logits, seen_rows, t_k, allowed_mask=chain["allowed"]
                )
                self.seen = sampler_mod.update_seen(
                    self.seen,
                    jnp.asarray(
                        np.where(
                            active, np.asarray(prep.slots)[lo:hi], -1
                        )
                    ),
                    out.tokens,
                )
                chain["outs"].append(out)
                chain["tokens"] = out.tokens  # stays on device

        # pack every chain's K results ON DEVICE into one buffer
        # (sampler.pack_output) and concatenate across chains there
        # too: the host pulls ONE buffer per wave instead of 5 per
        # (chain, step)
        packed_dev = []
        for chain in chains:
            outs = chain["outs"]
            stacked = sampler_mod.SamplerOutput(
                tokens=jnp.stack([o.tokens for o in outs]),
                logprob=jnp.stack([o.logprob for o in outs]),
                rank=jnp.stack([o.rank for o in outs]),
                topn_ids=jnp.stack([o.topn_ids for o in outs]),
                topn_logprobs=jnp.stack([o.topn_logprobs for o in outs]),
            )
            packed_dev.append(sampler_mod.pack_output(stacked))
        host = _HostSamplerOutput.from_packed(
            jnp.concatenate(packed_dev, axis=1)  # [K, B, 3+2W]
        )
        return [
            [host.token(k, i) for k in range(prep.steps_per_seq[i])]
            for i in range(prep.num_seqs)
        ]
