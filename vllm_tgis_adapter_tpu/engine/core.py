"""Synchronous engine core: request admission → step loop → outputs.

The TPU-native engine beneath the serving layer.  Together with
``async_llm.AsyncLLMEngine`` it satisfies the capability surface the
reference adapter consumes from vLLM (SURVEY.md §2.3): add/abort requests,
continuous batching, per-step sampling, incremental detokenization, stop
detection, and per-request timing metrics (reference consumption points:
grpc_server.py:205-225, tgis_utils/logs.py:193-202).
"""

from __future__ import annotations

import time
from typing import Optional

from vllm_tgis_adapter_tpu.engine.config import EngineConfig
from vllm_tgis_adapter_tpu.engine.detokenizer import IncrementalDetokenizer
from vllm_tgis_adapter_tpu.engine.outputs import Logprob, RequestOutput
from vllm_tgis_adapter_tpu.engine.runner import (
    SYNC_DISPATCH,
    ModelRunner,
    PromptLogprobInfo,
    SampledToken,
)
from vllm_tgis_adapter_tpu.engine import sanitizer
from vllm_tgis_adapter_tpu.engine.sampling_params import (
    RequestOutputKind,
    SamplingParams,
)
from vllm_tgis_adapter_tpu.engine.scheduler import (
    DecodePlan,
    PrefillPlan,
    RaggedPlan,
    Scheduler,
)
from vllm_tgis_adapter_tpu.engine.sequence import Sequence, SequenceStatus
from vllm_tgis_adapter_tpu import metrics
from vllm_tgis_adapter_tpu.flight_recorder import (
    DECODE_PROGRESS_EVERY,
    FlightRecorder,
)
from vllm_tgis_adapter_tpu.supervisor import failpoints
from vllm_tgis_adapter_tpu.logging import init_logger

logger = init_logger(__name__)


def describe_plan(plan) -> Optional[dict]:  # noqa: ANN001
    """Small JSON-safe summary of a dispatch plan (the "in-flight batch
    plan" line of watchdog dumps and /debug/state)."""
    if plan is None:
        return None
    if isinstance(plan, RaggedPlan):
        return {
            "kind": "ragged",
            "bucket": plan.token_bucket,
            "total_tokens": plan.total_tokens,
            "num_decode": sum(1 for i in plan.items if i.is_decode),
            "num_prefill": sum(1 for i in plan.items if not i.is_decode),
            "num_verify": sum(
                1 for i in plan.items if i.spec_width > 0
            ),
            "fill_ratio": round(
                plan.total_tokens / plan.token_bucket, 4
            ) if plan.token_bucket else 0.0,
            "request_ids": [i.seq.request_id for i in plan.items],
        }
    if isinstance(plan, PrefillPlan):
        return {
            "kind": "prefill",
            "bucket": plan.bucket_len,
            "request_id": plan.seq.request_id,
            "start_pos": plan.start_pos,
            "chunk_tokens": len(plan.token_ids),
            "is_final": plan.is_final,
        }
    return {
        "kind": "decode",
        "batch_bucket": plan.batch_bucket,
        "num_seqs": len(plan.seqs),
        "num_steps": plan.num_steps,
        "request_ids": [s.request_id for s in plan.seqs],
    }


class LLMEngine:
    """Single-process engine: one model, one scheduler, one device program."""

    def __init__(self, config: EngineConfig, model, params, tokenizer,
                 mesh=None, memory_device=None, pp_devices=None):
        if config.cache_config.num_blocks <= 0:
            # auto-size the KV pool from free HBM now that the weights are
            # resident (reference behavior: vLLM's gpu_memory_utilization)
            import dataclasses as _dc

            from vllm_tgis_adapter_tpu.engine.kv_cache import (
                resolve_num_blocks,
            )

            size_cfg = config
            pp = config.parallel_config.pipeline_parallel_size
            if pp > 1:
                # each device stores only its stage's layer slice, so a
                # block costs num_layers/pp of the whole-model estimate;
                # size against the LARGEST stage so every stage fits
                from vllm_tgis_adapter_tpu.engine.pipeline import (
                    split_layer_ranges,
                )

                stage_layers = max(
                    hi - lo
                    for lo, hi in split_layer_ranges(
                        config.model_config.num_layers, pp
                    )
                )
                size_cfg = _dc.replace(
                    config,
                    model_config=_dc.replace(
                        config.model_config, num_layers=stage_layers
                    ),
                )
            config = _dc.replace(
                config,
                cache_config=_dc.replace(
                    config.cache_config,
                    num_blocks=resolve_num_blocks(size_cfg, memory_device),
                ),
            )
        self.config = config
        self.tokenizer = tokenizer
        if config.parallel_config.pipeline_parallel_size > 1:
            from vllm_tgis_adapter_tpu.engine.pipeline import PipelineRunner

            self.runner = PipelineRunner(config, model, params,
                                         devices=pp_devices)
        else:
            self.runner = ModelRunner(config, model, params, mesh=mesh)
        self.scheduler = Scheduler(
            config.scheduler_config,
            config.cache_config,
            config.cache_config.num_blocks,
            max_model_len=config.max_model_len,
        )
        # ragged unified data path — THE serving planner
        # (docs/ATTENTION.md): the scheduler plans token-budgeted
        # RaggedPlans.  The legacy solo-prefill/fused-decode alternation
        # serves only pp>1 / sp>1 engines (no ragged plumbing through
        # the staged runner / sp ring yet) and prompt-logprob heads.
        # The RUNNER's mesh is authoritative for sp — callers (dp
        # replicas, the multichip dry run) may pass a mesh explicitly
        # without it appearing in parallel_config
        mcfg = config.model_config
        pcfg = config.parallel_config
        runner_mesh = getattr(self.runner, "mesh", None)
        mesh_sp = (
            dict(runner_mesh.shape).get("sp", 1)
            if runner_mesh is not None
            else 1
        )
        self.scheduler.ragged = (
            pcfg.pipeline_parallel_size == 1
            and pcfg.sequence_parallel_size == 1
            and mesh_sp == 1
        )
        # rolling-window KV eviction (scheduler docstring for the gates)
        if (
            mcfg.sliding_window > 0
            and mcfg.max_window_layers == 0
            and not config.cache_config.enable_prefix_caching
            and config.speculative is None
        ):
            self.scheduler.rolling_window = mcfg.sliding_window
        # --swap-space host KV swap for preemption victims.  Gates: the
        # flat ModelRunner cache only (pp stages split the layer axis),
        # and no rolling-window eviction (evicted low pages make the
        # [0, n) slot range unsaveable — recompute is cheap there anyway)
        self._swap_budget = int(config.swap_space_gib * (1 << 30))
        self._swap_used = 0
        if (
            self._swap_budget > 0
            and pcfg.pipeline_parallel_size == 1
            and self.scheduler.rolling_window == 0
        ):
            self.scheduler.swap_out_fn = self._swap_out_seq
            self.scheduler.swap_drop_fn = self._swap_drop_seq
        # host-RAM KV tier (--kv-host-cache-gb, engine/kv_tier.py): a
        # hash-addressed prefix-page store behind the swap machinery —
        # registered prompt pages demote device→host asynchronously,
        # prefix-cache misses the tier can cover PARK for an async
        # promotion, and preemption swap-out lands in the same store.
        # Same gates as --swap-space (flat ModelRunner cache only, no
        # rolling-window eviction).  0 (library default) is byte-
        # identical to the pre-tier engine; dp fleets and supervised
        # rebuilds re-attach a shared/surviving tier via adopt_kv_tier.
        self.kv_tier = None
        self._promotions: list = []  # (seq, ticket) awaiting apply
        self.kv_host_promoted_tokens = 0
        # fleet-level telemetry hooks (telemetry/): attached by the
        # async engine at build AND after every supervised rebuild —
        # None for direct core users, and every call site guards on it
        self.slo = None  # telemetry.slo.SloEngine
        self.ledger = None  # telemetry.ledger.CostLedger
        if (
            config.kv_host_cache_gb > 0
            and pcfg.pipeline_parallel_size == 1
            and self.scheduler.rolling_window == 0
        ):
            from vllm_tgis_adapter_tpu.engine.kv_tier import HostKVTier

            self.kv_tier = HostKVTier(
                round(config.kv_host_cache_gb * (1 << 30)),
                config.cache_config.block_size,
            )
            if config.kv_disk_cache_gb > 0:
                # disk rung beneath host RAM (--kv-disk-cache-gb,
                # docs/MEMORY.md): host LRU victims — KV pages and
                # spilled adapters — cascade down; promotions walk
                # disk→host→device through the same park/promote gate
                from vllm_tgis_adapter_tpu.engine.kv_tier import (
                    DiskKVTier,
                )

                self.kv_tier.attach_disk(DiskKVTier(
                    round(config.kv_disk_cache_gb * (1 << 30)),
                    directory=config.kv_disk_cache_dir,
                    block_size=config.cache_config.block_size,
                ))
            self._wire_kv_tier()
        elif config.kv_host_cache_gb > 0:
            logger.warning(
                "--kv-host-cache-gb has no effect with pp > 1 or "
                "rolling-window KV eviction; host KV tier disabled"
            )
        # black-box lifecycle recorder (flight_recorder.py): every
        # admission/dispatch/preemption/finish appends one bounded ring
        # entry; the scheduler shares it for preemption events
        self.recorder = FlightRecorder()
        self.scheduler.recorder = self.recorder
        # step-time anatomy ring (telemetry/steptime.py): the step loop
        # stamps phase boundaries below and commit_step finalizes one
        # StepRecord per dispatch; per-engine like the recorder, so a
        # supervised rebuild starts a fresh ring with no re-attach
        from vllm_tgis_adapter_tpu.telemetry.steptime import (
            StepTimeline,
            backend_dispatch_blocks,
        )

        self.steptime = StepTimeline(
            dispatch_blocks=backend_dispatch_blocks()
        )
        # monotonically increasing dispatch counter; stamps recorder
        # events so "which wave was in flight" is answerable post-hoc
        self.step_counter = 0
        # dp replica index (AsyncLLMEngine stamps it on every replica's
        # engine, and on rebuilt replacements): the `replica` label on
        # the per-dispatch step/occupancy metrics
        self.replica_index = 0
        # prefill/decode disaggregation (docs/SCALING.md "Disaggregated
        # roles"): stamped by the async layer via set_replica_role.  A
        # 'prefill' engine stages every sequence that samples its first
        # token into pending_handoffs at commit (the async layer drains
        # them onto decode-capable replicas); 'mixed' (default) is the
        # pre-disaggregation behavior.
        self.replica_role = "mixed"
        self.pending_handoffs: list = []
        self._seqs: dict[str, Sequence] = {}
        # explicit device slice (from_config sets it under dp/pp); the
        # supervisor's rebuild reuses it so a replacement engine lands
        # on the devices this replica owns
        self._devices = None
        self._lora_tokenizers: dict[str, object] = {}
        # adapter registry consumed by the gRPC adapter store
        # (grpc/adapters.py) and by the runner's device residency —
        # the paged pool (engine/adapter_pool.py) when the runner built
        # one, else the legacy sync_lora stacked tensors
        from vllm_tgis_adapter_tpu.engine.lora import LoRAManager

        pool = getattr(self.runner, "adapter_pool", None)
        self.lora_manager = LoRAManager(
            config.lora_config.max_loras,
            config.lora_config.max_lora_rank,
            moe_model=config.model_config.num_experts > 0,
            max_cpu_loras=(
                config.lora_config.resolved_max_cpu_loras()
                if pool is not None
                else 0
            ),
        )
        if pool is not None:
            pool.manager = self.lora_manager
            self.lora_manager.attach_pool(pool)
            # adapter-affinity scheduling: rows whose adapter is still
            # streaming PARK instead of blocking the batch
            self.scheduler.lora_gate = self._lora_gate
        elif config.lora_config.enabled:
            # legacy slow path: registry changes rebuild the stacks OFF
            # the event loop at load time (satellite of the pool work)
            self.lora_manager.add_resync(self)
        if self.kv_tier is not None and self.kv_tier.disk is not None:
            # cold adapters ride the same disk rung as cold KV pages:
            # host-registry evictions spill, later requests restore
            self.lora_manager.attach_disk_tier(self.kv_tier.disk)
        # unified paged HBM arena (engine/arena.py, docs/MEMORY.md):
        # adapter residency and KV pages draw from ONE block budget
        # with unified LRU + pinning.  Built only where both sides
        # exist (a paged adapter pool over the flat runner's
        # allocator); --no-unified-arena restores split budgets.
        self.arena = None
        if (
            pool is not None
            and config.unified_arena
            and config.parallel_config.pipeline_parallel_size == 1
        ):
            from vllm_tgis_adapter_tpu.engine.arena import UnifiedArena
            from vllm_tgis_adapter_tpu.engine.kv_cache import (
                _lora_stack_bytes,
                per_block_bytes,
            )

            alloc = self.scheduler.allocator
            page_bytes = per_block_bytes(config)
            self.arena = UnifiedArena(
                alloc,
                kv_page_bytes=page_bytes,
                min_kv_reserve=alloc.blocks_needed(config.max_model_len),
                # the padded slot stacks' boot-time HBM reservation, in
                # page units: adapter charges consume it before any KV
                # page is borrowed (resolve_num_blocks already priced
                # it out of the KV pool — charging the KV pool again
                # would double-count)
                adapter_budget_pages=-(
                    -_lora_stack_bytes(config) // page_bytes
                ),
            )
            alloc.arena = self.arena
            self.arena.attach_pool(pool)
            pool.arena = self.arena

    # ------------------------------------------------------------- lifecycle

    @classmethod
    def from_config(cls, config: EngineConfig, devices=None) -> "LLMEngine":
        """Build one engine replica.  ``devices``: explicit device slice
        this replica owns (dp replicas get disjoint slices from
        AsyncLLMEngine.from_config); None = all visible devices."""
        from transformers import AutoTokenizer

        from vllm_tgis_adapter_tpu.engine.weights import load_model_params
        from vllm_tgis_adapter_tpu.models import get_model_class

        from vllm_tgis_adapter_tpu.parallel import (
            make_place_fn,
            validate_tp_divisibility,
        )
        from vllm_tgis_adapter_tpu.parallel.mesh import (
            mesh_from_parallel_config,
        )

        if (
            config.parallel_config.data_parallel_size > 1
            or config.parallel_config.dp_replicas > 1
        ):
            # LLMEngine is always ONE dp rank: AsyncLLMEngine builds the
            # replica fleet and hands each LLMEngine a dp=1 config plus
            # its device slice.  Rejecting here (not per-branch) keeps
            # the pp and non-pp paths consistent — a dp>1 config (either
            # spelling) can never silently run at 1/dp capacity.
            raise ValueError(
                "LLMEngine is one dp replica; construct via "
                "AsyncLLMEngine.from_config for --data-parallel-size / "
                "--dp-replicas replicas"
            )
        mcfg = config.model_config
        pcfg = config.parallel_config
        if (
            mcfg.moe_dispatch == "capacity"
            and not mcfg.moe_record_drops
            and pcfg.tensor_parallel_size
            * pcfg.pipeline_parallel_size
            * pcfg.sequence_parallel_size == 1
        ):
            # observable capacity drops (metrics.py record_moe_dispatch);
            # multi-device meshes skip the host callback — it would run
            # per-shard inside the SPMD program and stall collectives
            import dataclasses as _dc

            mcfg = _dc.replace(mcfg, moe_record_drops=True)
        model_cls = get_model_class(mcfg.model_type)
        model = model_cls(mcfg)
        # build the mesh BEFORE loading so every tensor is sharded onto it
        # as it is read — sharding after a full single-device load would
        # OOM device 0 for models that need TP in the first place
        mesh = None
        pp = config.parallel_config.pipeline_parallel_size
        if pp > 1:
            # stage-routed placement: each layer's tensors land directly
            # on its pipeline stage's device group (engine/pipeline.py)
            from vllm_tgis_adapter_tpu.engine.pipeline import (
                make_pp_place_fn,
            )

            place = make_pp_place_fn(config, devices=devices)
        else:
            mesh = mesh_from_parallel_config(
                config.parallel_config, devices=devices
            )
            place = None
            if mesh is not None:
                validate_tp_divisibility(mcfg, mesh.shape["tp"])
                place = make_place_fn(mesh)
        logger.info("loading weights from %s", mcfg.model)
        params = load_model_params(mcfg, mcfg.model, place=place)
        if config.quantization == "int8":
            # weight-only int8 after (possibly sharded) load; the KV pool
            # auto-sizing below sees the freed HBM.  The draft model (if
            # any) stays in the model dtype: it is small by construction
            # and its logits feed acceptance tests directly.
            from vllm_tgis_adapter_tpu.engine.weights import (
                quantize_params_int8,
            )

            params = quantize_params_int8(params)
            logger.info("quantized projection weights to int8 "
                        "(weight-only, per-out-channel scales)")

        # the draft loads BEFORE the engine so the KV-pool auto-sizing
        # (resolve_num_blocks, driven by post-weights free HBM) sees the
        # draft's parameter footprint too
        draft_model = draft_params = None
        if config.speculative is not None:
            spec = config.speculative
            logger.info(
                "loading speculative draft weights from %s", spec.draft_model
            )
            draft_cfg = spec.draft_model_config
            draft_model = get_model_class(draft_cfg.model_type)(draft_cfg)
            draft_params = load_model_params(
                draft_cfg, spec.draft_model, place=place
            )
            # calibrated kv-scale floors are a TARGET-cache feature
            # (runner pops them for the main params): the draft's
            # cache follows the target scheme and greedy acceptance
            # compares TARGET logits, so a calibrated draft checkpoint
            # must not leak this non-layer key into the draft pytree
            # (shard_llama_params / jitted programs would choke on it)
            if isinstance(draft_params, dict):
                draft_params.pop("kv_scale_floors", None)

        tokenizer = AutoTokenizer.from_pretrained(
            config.tokenizer or mcfg.model,
            revision=config.revision,
            trust_remote_code=config.trust_remote_code,
        )
        # KV auto-sizing must read free HBM from a device THIS replica
        # owns: under dp, device 0 belongs to replica 0 and is already
        # full of replica-0 weights by the time later replicas size
        # their pools
        memory_device = devices[0] if devices else None
        engine = cls(config, model, params, tokenizer, mesh=mesh,
                     memory_device=memory_device, pp_devices=devices)
        # remembered for supervised rebuild (supervisor/supervisor.py):
        # a replacement engine must own the SAME device slice — under dp
        # the other slices hold other replicas' weights and pools
        engine._devices = devices
        if draft_model is not None:
            engine.attach_speculative(draft_model, draft_params)
        return engine

    def attach_speculative(self, draft_model, draft_params) -> None:  # noqa: ANN001
        """Attach the draft model (speculative decoding): the runner
        builds the propose + jitted ragged-verify programs and the
        scheduler starts planning verify spans for spec-eligible rows
        (docs/ATTENTION.md "Speculative decoding")."""
        self.runner.attach_speculative(draft_model, draft_params)
        if not self.scheduler.ragged:
            # legacy-planner engine (an explicitly passed sp mesh the
            # config-level refusals cannot see): the draft would sit
            # resident without a verify span ever planned
            logger.warning(
                "speculative draft attached to a legacy-planner engine "
                "(pp/sp): verify spans ride the ragged planner only — "
                "speculation will not run (docs/ATTENTION.md)"
            )
        if self.config.speculative is not None:
            self.scheduler.set_spec_gamma(
                self.config.speculative.num_speculative_tokens
            )

    def get_tokenizer(self, lora_request=None):  # noqa: ANN001
        """Base tokenizer, or the adapter's own if its directory ships
        tokenizer files (reference behavior: per-LoRA tokenizers,
        /root/reference/src/vllm_tgis_adapter/grpc/grpc_server.py:648-652).
        """
        path = getattr(lora_request, "lora_path", None)
        if not path:
            return self.tokenizer
        cached = self._lora_tokenizers.get(path)
        if cached is not None:
            return cached
        import os

        has_tok = any(
            os.path.exists(os.path.join(path, f))
            for f in ("tokenizer.json", "tokenizer_config.json",
                      "tokenizer.model")
        )
        tok = self.tokenizer
        if has_tok:
            from transformers import AutoTokenizer

            tok = AutoTokenizer.from_pretrained(
                path, trust_remote_code=self.config.trust_remote_code
            )
        self._lora_tokenizers[path] = tok
        return tok

    def get_model_config(self):
        return self.config.model_config

    # -------------------------------------------------------------- requests

    def add_request(
        self,
        request_id: str,
        prompt: Optional[str],
        params: SamplingParams,
        *,
        prompt_token_ids: Optional[list[int]] = None,
        arrival_time: Optional[float] = None,
        lora_name: Optional[str] = None,
        trace_id: Optional[str] = None,
        deadline: Optional[float] = None,
        tenant_id: Optional[str] = None,
        request_class: Optional[str] = None,
    ) -> None:
        if request_id in self._seqs:
            raise ValueError(f"duplicate request_id {request_id!r}")
        if prompt_token_ids is None:
            if prompt is None:
                raise ValueError("either prompt or prompt_token_ids required")
            prompt_token_ids = self.tokenizer(prompt).input_ids
        max_len = self.config.max_model_len
        if len(prompt_token_ids) >= max_len:
            raise ValueError(
                f"prompt length {len(prompt_token_ids)} exceeds "
                f"max_model_len {max_len}"
            )
        seq = Sequence(
            request_id,
            prompt,
            list(prompt_token_ids),
            params,
            arrival_time=arrival_time,
            fallback_seed=self.runner.new_fallback_seed(),
            lora_name=lora_name,
        )
        seq.trace_id = trace_id
        seq.tenant_id = tenant_id
        if request_class is not None:
            seq.request_class = request_class
        # queue TTL (frontdoor): the async layer passes the effective
        # deadline (request SLO ∧ arrival + --queue-ttl, stamped before
        # any fair-queue parking); direct core users get the same
        # tightening from THEIR arrival time here
        fd = getattr(self.config, "frontdoor", None)
        if (
            fd is not None
            and fd.enabled
            and fd.queue_ttl_s > 0
            # precompile warmups (__warmup_*) wait behind tens of
            # seconds of XLA compiles by design — a TTL shed there
            # would silently lose bucket coverage
            and not request_id.startswith("__warmup")
        ):
            ttl_deadline = seq.metrics.arrival_time + fd.queue_ttl_s
            deadline = (
                ttl_deadline
                if deadline is None
                else min(deadline, ttl_deadline)
            )
        seq.deadline = deadline
        self._prepare_admission(seq)
        self._commit_admission(seq)
        self.recorder.record(
            "admit", request_id, step=self.step_counter, trace_id=trace_id,
            prompt_tokens=len(prompt_token_ids),
            **({"lora": lora_name} if lora_name else {}),
        )

    def _prepare_admission(self, seq: Sequence) -> None:
        """Per-request machinery shared by fresh admission and decode
        resume: adapter residency, speculative eligibility, FSM
        compilation (left at its init state — resume replays it), and
        the incremental detokenizer (empty — resume replays it)."""
        params = seq.params
        lora_name = seq.lora_name
        pool = getattr(self.runner, "adapter_pool", None)
        if pool is None:
            seq.lora_slot = self.lora_manager.slot_of(lora_name)
        else:
            # pool mode: the slot is resolved at SCHEDULE time by the
            # adapter gate once the weights are device-resident; issue
            # the prefetch NOW so the host→device stream overlaps the
            # queue wait (and a supervised rebuild re-streams exactly
            # the adapters its replayed/resumed requests reference)
            seq.lora_slot = 0
            if lora_name is not None:
                pool.note_lookup(lora_name, replica=self.replica_index)
                resident = pool.prefetch(lora_name)
                if not resident and self.ledger is not None:
                    # cost attribution: this admission is the one that
                    # pulls the adapter onto the device
                    self.ledger.note_adapter_swap(seq.request_id)
        if self.runner.spec is not None:
            from vllm_tgis_adapter_tpu.engine.speculative import (
                spec_eligible,
            )

            # greedy rows verify by argmax match, sampled rows by
            # rejection sampling; LoRA rows verify through the adapted
            # target (engine/speculative.py spec_eligible)
            seq.spec_eligible = spec_eligible(params)
        if params.structured_outputs is not None:
            from vllm_tgis_adapter_tpu.engine.constrained import compile_fsm

            seq.fsm = compile_fsm(
                params.structured_outputs,
                self.tokenizer,
                self.config.model_config.eos_token_id,
            )
            seq.fsm_state = seq.fsm.init_state
        seq.detokenizer = IncrementalDetokenizer(
            self.tokenizer,
            seq.prompt_token_ids,
            skip_special_tokens=params.skip_special_tokens,
        )

    def _commit_admission(self, seq: Sequence) -> None:
        """Hand a fully prepared sequence to the scheduler.

        Pinned only once admission can no longer fail — an exception in
        preparation must not leak a ref no finish path will release;
        the pin covers the sequence's whole lifetime (incl.
        preemption-resume: eviction must not reassign a slot a running
        row still indexes)."""
        self.lora_manager.pin(seq.lora_name)
        self._seqs[seq.request_id] = seq
        self.scheduler.add(seq)

    def abort_request(self, request_id: str) -> Optional[RequestOutput]:
        seq = self._seqs.pop(request_id, None)
        if seq is None or seq.is_finished:
            return None
        self.scheduler.abort(request_id)
        self.lora_manager.unpin(seq.lora_name)
        seq.metrics.finished_time = time.time()
        self.recorder.record(
            "abort", request_id, step=self.step_counter,
            trace_id=seq.trace_id, output_tokens=seq.num_output_tokens,
        )
        return seq.to_request_output()

    def has_unfinished_requests(self) -> bool:
        # newly_finished counts: a scheduler-rejected/shed request's
        # final output is emitted by the NEXT plan_step — the step loop
        # must not park before that drain or the client hangs
        return (
            self.scheduler.num_unfinished > 0
            or bool(self.scheduler.newly_finished)
        )

    # ---------------------------------------------------------------- LoRA

    def _lora_gate(self, seq: Sequence) -> bool:
        """Scheduler adapter gate (pool mode): True when ``seq``'s
        adapter is device-resident (slot resolved onto the sequence);
        False parks the request while the pool streams it in."""
        name = seq.lora_name
        if name is None:
            return True
        slot = self.runner.adapter_pool.ensure_resident(name)
        if slot is None:
            return False
        seq.lora_slot = slot
        return True

    def adopt_lora_manager(self, manager) -> None:  # noqa: ANN001
        """Point this engine at a shared/survivor adapter registry (dp
        fleet construction, supervised rebuild) and re-attach the
        runner's pool (or legacy resync hook) to it."""
        self.lora_manager = manager
        pool = getattr(self.runner, "adapter_pool", None)
        if pool is not None:
            pool.manager = manager
            manager.attach_pool(pool)
        elif self.config.lora_config.enabled:
            manager.add_resync(self)
        if (
            self.kv_tier is not None
            and self.kv_tier.disk is not None
        ):
            manager.attach_disk_tier(self.kv_tier.disk)

    # -------------------------------------------------------------- KV swap

    def _swap_out_seq(self, seq: Sequence) -> bool:
        """Preemption hook (scheduler._preempt_youngest): copy the
        victim's computed KV to host within the --swap-space budget.
        Cache coverage invariant between dispatches: positions
        [0, num_tokens-1) are written; the next decode writes
        num_tokens-1."""
        n = seq.num_tokens - 1
        if n <= 0 or seq.blocks is None:
            return False
        if self.kv_tier is not None:
            # the victim's full pages ALSO land in the hash-addressed
            # host tier: the per-seq swap copy below restores this one
            # request, the tier serves every future request sharing the
            # prefix (and survives an engine restart)
            self._tier_demote(seq, seq.all_token_ids, written=n)
        slots = seq.blocks.slots_for_range(0, n)
        k_cache, _ = self.runner.caches
        per_slot = (
            2 * k_cache.shape[0] * k_cache.shape[1] * k_cache.shape[3]
            * k_cache.dtype.itemsize
        )
        nbytes = per_slot * len(slots)
        if self._swap_used + nbytes > self._swap_budget:
            logger.info(
                "swap-space full (%d/%d bytes): request %s falls back to "
                "recompute", self._swap_used, self._swap_budget,
                seq.request_id,
            )
            return False
        k_host, v_host = self.runner.extract_kv(slots)
        seq.swapped = (k_host, v_host, n, nbytes)
        self._swap_used += nbytes
        seq.metrics.events.append(("swap_out", time.time_ns()))
        self.recorder.record(
            "swap_out", seq.request_id, step=self.step_counter,
            trace_id=seq.trace_id, tokens=n, bytes=nbytes,
        )
        metrics.kv_swap_out_total.labels(
            replica=str(self.replica_index)
        ).inc()
        # inc/dec (not set): dp replicas share the process-global gauge,
        # so absolute sets from different replicas would clobber
        metrics.kv_swap_used_bytes.inc(nbytes)
        return True

    def _swap_drop_seq(self, seq: Sequence) -> None:
        """Release a held host copy (recompute admission won the race)."""
        if seq.swapped is not None:
            self._swap_used -= seq.swapped[3]
            metrics.kv_swap_used_bytes.dec(seq.swapped[3])

    def _drain_swap_ins(self) -> None:
        """Restore swapped queue heads on a clean dispatch boundary (the
        caches rebind must not race an in-flight dispatch's commit)."""
        while True:
            seq = self.scheduler.try_swap_in()
            if seq is None:
                return
            k_host, v_host, n, nbytes = seq.swapped
            self.runner.restore_kv(
                seq.blocks.slots_for_range(0, n), k_host, v_host
            )
            # the new batch row may hold a stale seen-token matrix from a
            # previous occupant; prefill's seeding is skipped on swap-in
            self.runner.reseed_seen_row(seq.slot, seq.all_token_ids)
            seq.swapped = None
            self._swap_used -= nbytes
            seq.metrics.events.append(("swap_in", time.time_ns()))
            self.recorder.record(
                "swap_in", seq.request_id, step=self.step_counter,
                trace_id=seq.trace_id, tokens=n,
            )
            metrics.kv_swap_in_total.labels(
                replica=str(self.replica_index)
            ).inc()
            metrics.kv_swap_used_bytes.dec(nbytes)
            logger.info("restored request %s from host swap (%d tokens)",
                        seq.request_id, n)

    # --------------------------------------------------------- host KV tier

    def _wire_kv_tier(self) -> None:
        self.scheduler.kv_gate = self._kv_tier_gate
        if self.config.cache_config.enable_prefix_caching:
            # eviction → demotion: a registered page copies to the host
            # tier at the moment the device LRU reclaims it — never
            # earlier, so pages the device keeps (or that are never
            # reused) cost no transfer (ISSUE 9 integration point 1)
            self.scheduler.allocator.evict_hook = self._tier_evict_demote
        if self.scheduler.swap_out_fn is None:
            # no --swap-space: preemption victims demote their computed
            # full pages into the hash-addressed store instead (resume
            # then recomputes only the uncovered tail via promotion)
            self.scheduler.swap_out_fn = self._tier_swap_out

    def set_replica_role(self, role: str) -> None:
        """Stamp this replica's disaggregation role (async layer /
        supervisor rebuild).  A decode replica's admission throat is the
        in-flight-promotion bound — every handoff arrives as a parked
        promotion — so it gets a wider bound than the mixed default
        (each parked promotion still reserves its full prompt pages;
        the kv gate's recompute fallback stays the overflow valve)."""
        self.replica_role = role
        self.scheduler.role = role
        if role == "decode":
            self.MAX_INFLIGHT_PROMOTIONS = 32
        else:
            # restore the class default on re-role: a widened bound
            # left behind on a now-mixed replica would let 32 parked
            # promotions reserve full prompt capacity each — the
            # pool-thrash the default of 8 exists to prevent
            self.__dict__.pop("MAX_INFLIGHT_PROMOTIONS", None)

    def adopt_kv_tier(self, tier) -> None:  # noqa: ANN001
        """Point this engine at a shared/surviving host KV tier (dp
        fleet construction, supervised rebuild).  The construction-time
        fresh tier (if any) is discarded; in-flight promotion tickets
        stay with the engine that issued them — their target pages
        belong to that engine's (possibly dead) pool."""
        if tier is None:
            return
        if self.config.parallel_config.pipeline_parallel_size > 1:
            return  # no flat cache to gather/scatter against
        self.kv_tier = tier
        self._wire_kv_tier()
        if tier.disk is not None:
            # adapter spill/restore follows the surviving tier's disk
            self.lora_manager.attach_disk_tier(tier.disk)

    def _tier_demote(
        self,
        seq: Sequence,
        token_ids: list[int],
        written: Optional[int] = None,
    ) -> int:
        """Queue ``seq``'s full pages of ``token_ids`` that the host
        tier lacks: per-page jitted device gathers are ENQUEUED here
        (ordered before any later dispatch that could overwrite the
        pages, so the read content is the content current now), and the
        tier's worker thread completes the device→host copies off the
        event loop.  Returns the number of pages queued.

        ``written`` caps demotion at the cache-coverage frontier: a
        page may only tier when EVERY position it covers has its K/V
        written.  Preemption passes ``num_tokens - 1`` (the invariant
        ``_swap_out_seq`` documents: the just-sampled token's slot is
        written by the NEXT dispatch) — without the cap, the last page
        could carry one garbage position into the hash-addressed store
        and poison every future chain extension through it."""
        tier = self.kv_tier
        if tier is None or seq.blocks is None:
            return 0
        bs = self.config.cache_config.block_size
        limit = len(token_ids) if written is None else min(
            len(token_ids), written
        )
        pages = min(limit // bs, len(seq.blocks.blocks))
        if pages <= 0:
            return 0
        from vllm_tgis_adapter_tpu.engine.kv_cache import chain_digests

        digests = chain_digests(token_ids, bs, seq.lora_name, pages)
        batch = []
        for p in range(pages):
            if tier.has(digests[p]) or seq.blocks.blocks[p] < 0:
                continue
            start = p * bs
            # the gathered tuple is (k, v) — plus the per-head scale
            # columns under --kv-quantization — stored verbatim so the
            # eventual restore is bit-exact
            batch.append((
                digests[p],
                *self.runner.gather_kv_block(
                    seq.blocks.slots_for_range(start, start + bs)
                ),
            ))
        if not batch:
            return 0
        tier.submit(batch)
        self.recorder.record(
            "demote_host", seq.request_id, step=self.step_counter,
            trace_id=seq.trace_id, pages=len(batch),
        )
        if self.ledger is not None:
            self.ledger.note_tier_bytes(
                seq.request_id, len(batch) * self._tier_page_bytes()
            )
        return len(batch)

    def _tier_swap_out(self, seq: Sequence) -> bool:
        """Preemption hook when the tier is on and --swap-space is not:
        the victim's computed full pages land in the hash-addressed
        store (keyed over prompt ‖ generated tokens, so the resume's
        promotion walk matches), and re-admission recomputes only the
        uncovered tail.  Returns False — ``seq.swapped`` is never set;
        the store, not a per-sequence copy, owns the bytes."""
        self._tier_demote(
            seq, seq.all_token_ids, written=seq.num_tokens - 1
        )
        return False

    # cap on promotions in flight per engine: each parked promotion
    # reserves its request's full prompt pages, so an unbounded warm
    # backlog could thrash the pool via preemption of its own parked
    # work; excess candidates simply admit on the recompute path
    MAX_INFLIGHT_PROMOTIONS = 8

    def _tier_evict_demote(self, digest: bytes, block: int) -> None:
        """Allocator evict hook: ONE registered page is about to be
        reclaimed — gather it now (device-ordered before the reclaiming
        owner's first write) and hand it to the tier's async committer.
        Runs under the engine lock inside planning/admission."""
        tier = self.kv_tier
        if tier is None or tier.has(digest):
            return
        bs = self.config.cache_config.block_size
        tier.submit([(
            digest,
            *self.runner.gather_kv_block(
                list(range(block * bs, (block + 1) * bs))
            ),
        )])
        self.recorder.record(
            "demote_host", step=self.step_counter, pages=1, block=block,
        )

    def _register_prefix(self, seq: Sequence) -> None:
        """Publish a completed prefill's pages for reuse: the device
        prefix cache, whose LRU eviction then demotes to the host tier
        (``_tier_evict_demote``) — or, when --enable-prefix-caching is
        OFF and only the host tier serves reuse, demote the (final,
        fully written) prompt pages directly at this commit."""
        self.scheduler.register_prefix(seq)
        if (
            self.kv_tier is not None
            and not self.config.cache_config.enable_prefix_caching
        ):
            self._tier_demote(seq, seq.prompt_token_ids)

    def _kv_tier_gate(self, seq: Sequence, start: bool = True) -> bool:
        """Scheduler kv gate: True = admit normally; False = the request
        PARKS while its host-tier-resident prefix promotes to device.
        ``start=True`` (planning paths) may begin a promotion: target
        pages are allocated NOW (device prefix hits adopted first, the
        host span on fresh pages) and the tier stages the transfer off
        the loop; ``start=False`` is a pure in-flight probe."""
        if seq.kv_promotion is not None:
            return False  # parked until _drain_promotions applies it
        if not start:
            return True
        if len(self._promotions) >= self.MAX_INFLIGHT_PROMOTIONS:
            # bound the pages parked promotions hold (each reserves its
            # full prompt capacity) and the transfer backlog: excess
            # warm candidates admit on the plain recompute path NOW and
            # later candidates re-gate once a promotion applies
            return True
        if (
            seq.prefill_pos != 0
            or seq.blocks is not None
            or seq.swapped is not None
            or seq.params.prompt_logprobs is not None  # _adoptable rule
        ):
            return True
        token_ids = seq.all_token_ids
        bs = self.config.cache_config.block_size
        max_pages = (len(token_ids) - 1) // bs  # match_prefix's cap
        if max_pages <= 0:
            return True
        alloc = self.scheduler.allocator
        matched = (
            alloc.peek_prefix(token_ids, seq.lora_name)
            if alloc.enable_prefix_caching
            else 0
        )
        start_page = matched // bs
        if start_page >= max_pages:
            return True  # device cache already covers everything usable
        # incremental probe: hashes only through the covered span, so a
        # cold-tier miss costs O(start_page + 1) hashes, not O(prompt)
        extra = self.kv_tier.peek_prefix_pages(
            token_ids, seq.lora_name, start_page
        )
        if extra <= 0:
            return True
        from vllm_tgis_adapter_tpu.engine.kv_cache import (
            SequenceBlocks,
            chain_digests,
        )

        digests = chain_digests(
            token_ids, bs, seq.lora_name, start_page + extra
        )
        lead = digests[start_page]
        for _, other in self._promotions:
            if not other.cancelled and lead in other.digests:
                # a sibling request is already streaming this span:
                # park WITHOUT a duplicate ticket — when the sibling
                # applies, its pages re-register in the device cache
                # and this request admits with device hits; if the
                # sibling fails, the next gate pass starts our own
                return False
        # promotion must not demand more than plain admission would: if
        # the pool cannot hold the whole prompt, let the normal path
        # wait/reject — never park a request the tier cannot unblock
        if not alloc.can_allocate(alloc.blocks_needed(len(token_ids))):
            return True
        seq.blocks = SequenceBlocks(alloc)
        if matched:
            hit_blocks, adopted = alloc.match_prefix(
                token_ids, seq.lora_name
            )
            seq.blocks.adopt(hit_blocks)
            start_page = adopted // bs  # same lock, but stay exact
        end_tokens = (start_page + extra) * bs
        # FULL prompt capacity, exactly like first-chunk admission
        # (which does ensure_capacity(total)): the post-promotion
        # mid-chunk continuation assumes every prompt page exists
        seq.blocks.ensure_capacity(len(token_ids))
        from vllm_tgis_adapter_tpu.engine.kv_tier import PromotionTicket

        ticket = PromotionTicket(
            request_id=seq.request_id,
            digests=digests[start_page:start_page + extra],
            start_tokens=start_page * bs,
            end_tokens=end_tokens,
        )
        seq.kv_promotion = ticket
        self._promotions.append((seq, ticket))
        self.kv_tier.start_promotion(ticket, self.runner._put)  # noqa: SLF001
        return False

    def _drain_promotions(self) -> None:
        """Apply completed host→device promotions on a clean dispatch
        boundary (the per-page scatter rebinds ``runner.caches``, same
        contract as swap-in).  An applied request resumes as a mid-chunk
        prefill AFTER the restored span; a failed/shrunk-to-zero ticket
        un-parks the request onto the plain recompute path."""
        if not self._promotions:
            return
        rest: list = []
        bs = self.config.cache_config.block_size
        alloc = self.scheduler.allocator
        for seq, ticket in self._promotions:
            if (
                ticket.cancelled
                or seq.kv_promotion is not ticket
                or self._seqs.get(seq.request_id) is not seq
            ):
                # aborted / preempted / belongs to a previous engine
                # incarnation: finish()/teardown released its pages
                if seq.kv_promotion is ticket:
                    seq.kv_promotion = None
                continue
            if not ticket.ready:
                rest.append((seq, ticket))
                continue
            if ticket.failed:
                seq.kv_promotion = None
                if seq.blocks is not None:
                    seq.blocks.release()
                    seq.blocks = None
                seq.prefill_pos = 0  # un-park; plain admission serves it
                continue
            if not self.scheduler._free_slots:  # noqa: SLF001
                rest.append((seq, ticket))  # retry next boundary
                continue
            for i, arrays in enumerate(ticket.pages):
                pos = ticket.start_tokens + i * bs
                self.runner.restore_kv_block(
                    seq.blocks.slots_for_range(pos, pos + bs),
                    *arrays,
                )
            seq.slot = self.scheduler._free_slots.pop()  # noqa: SLF001
            seq.prefill_pos = ticket.end_tokens
            seq.kv_promotion = None
            promoted = ticket.end_tokens - ticket.start_tokens
            alloc.prefix_hits += ticket.end_tokens
            alloc.prefix_lookup_tokens += len(seq.all_token_ids)
            self.kv_host_promoted_tokens += promoted
            self.kv_tier.note_promoted(len(ticket.pages), promoted)
            metrics.kv_prefix_tokens_reused_total.labels(
                tier="host"
            ).inc(promoted)
            if ticket.start_tokens:
                metrics.kv_prefix_tokens_reused_total.labels(
                    tier="device"
                ).inc(ticket.start_tokens)
            # the restored pages are now device content like any other:
            # publish them so the NEXT request hits on device directly
            alloc.register_prefix(
                seq.all_token_ids[:ticket.end_tokens],
                seq.blocks.blocks,
                seq.lora_name,
            )
            remote_pages = getattr(ticket, "remote_pages", 0)
            if remote_pages:
                # pages a kvnet peer served into this promotion: prefill
                # compute another HOST did (docs/CROSS_HOST.md) — priced
                # apart from the local host/disk rungs
                metrics.kv_prefix_tokens_reused_total.labels(
                    tier="remote"
                ).inc(remote_pages * bs)
                self.recorder.record(
                    "remote_hit", seq.request_id,
                    step=self.step_counter, trace_id=seq.trace_id,
                    pages=remote_pages, tokens=remote_pages * bs,
                )
            self.recorder.record(
                "promote_host", seq.request_id, step=self.step_counter,
                trace_id=seq.trace_id, tokens=promoted,
                pages=len(ticket.pages),
            )
            if self.ledger is not None:
                self.ledger.note_tier_bytes(
                    seq.request_id,
                    len(ticket.pages) * self._tier_page_bytes(),
                )
            logger.info(
                "request %s: %d prefix tokens promoted from the host KV "
                "tier (%d already device-resident)",
                seq.request_id, promoted, ticket.start_tokens,
            )
        self._promotions = rest

    # ------------------------------------- mid-decode checkpoint / resume

    def _tier_page_bytes(self) -> int:
        """K+V bytes of one KV page at the device cache dtype — the
        unit the cost ledger bills tier transfers in."""
        caches = getattr(self.runner, "caches", None)
        if not caches:
            return 0
        k_cache = caches[0]
        bs = self.config.cache_config.block_size
        # tpulint: disable=TPL202(static shape/dtype metadata only — .shape and .itemsize are host ints, no device value is pulled)
        return int(
            2 * k_cache.shape[0] * k_cache.shape[1] * k_cache.shape[3]
            * k_cache.dtype.itemsize * bs
        )

    def kv_pages_by_request(self) -> dict[str, int]:
        """{request_id: device KV pages currently held} over live
        sequences — the cost ledger's commit-boundary HBM occupancy
        sample (telemetry/ledger.py ``sample_kv``); warmups excluded."""
        out: dict[str, int] = {}
        for rid, seq in self._seqs.items():
            if rid.startswith("__warmup"):
                continue
            blocks = seq.blocks
            if blocks is not None:
                out[rid] = len(blocks.blocks)
        return out

    def checkpoint_decode(self, seq: Sequence):
        """Quiesce-time capture of one mid-decode request
        (docs/RECOVERY.md): demote its fully WRITTEN KV pages into the
        host tier (frontier-capped at ``num_tokens - 1`` — the
        just-sampled token's slot is written by a dispatch that died)
        and stage a ``DecodeCheckpoint`` alongside.  Returns the staged
        record, or None when the degradation ladder applies (tier off,
        ``--no-decode-resume``, checkpoint over the tier budget, or the
        gather itself failing on a wedged device) — the caller then
        falls back to the retryable ``EngineRestartError`` floor.

        Called by the supervisor's triage under the replica lock with
        the step loop reaped; the gathers are the same fixed-shape
        jitted per-page programs ordinary demotion uses, so a
        checkpoint never adds a compile shape.
        """
        tier = self.kv_tier
        if tier is None or not self.config.decode_resume:
            return None
        bs = self.config.cache_config.block_size
        token_ids = seq.all_token_ids
        written = seq.num_tokens - 1
        pages = max(0, written // bs)
        caches = getattr(self.runner, "caches", None)
        if pages and caches is not None:
            k_cache = caches[0]
            per_page = (
                2 * k_cache.shape[0] * k_cache.shape[1]
                * k_cache.shape[3] * k_cache.dtype.itemsize * bs
            )
            if pages * per_page > tier.budget_bytes:
                # can never fit — the store would evict the checkpoint's
                # own head while inserting its tail
                return None
        t0 = time.perf_counter()
        try:
            if (
                seq.status == SequenceStatus.RUNNING
                and seq.kv_promotion is None
                and seq.blocks is not None
            ):
                # gather the device-resident frontier.  Non-RUNNING
                # mid-decode states already demoted at their transition
                # (preemption swap-out lands in the tier; a parked
                # promotion's SOURCE pages are the tier) — their device
                # pages are absent or unwritten, so gathering here
                # would poison the store; the validation read decides.
                self._tier_demote(seq, token_ids, written=written)
        except Exception:  # noqa: BLE001 — a wedged device fails the ladder, not recovery
            logger.exception(
                "decode-checkpoint gather failed for request %s; "
                "falling back to retryable failure", seq.request_id,
            )
            return None
        from vllm_tgis_adapter_tpu.engine.kv_cache import chain_digests
        from vllm_tgis_adapter_tpu.engine.kv_tier import DecodeCheckpoint

        m = seq.metrics
        ckpt = DecodeCheckpoint(
            request_id=seq.request_id,
            prompt=seq.prompt,
            prompt_token_ids=list(seq.prompt_token_ids),
            output_token_ids=list(seq.output_token_ids),
            params=seq.params,
            fallback_seed=seq.fallback_seed,
            arrival_time=m.arrival_time,
            deadline=seq.deadline,
            tenant_id=seq.tenant_id,
            lora_name=seq.lora_name,
            trace_id=seq.trace_id,
            emitted_token_len=seq._emitted_token_len,  # noqa: SLF001
            emitted_text_len=seq._emitted_text_len,  # noqa: SLF001
            stop_scan_pos=seq.stop_scan_pos,
            output_logprobs=(
                list(seq.output_logprobs)
                if seq.output_logprobs is not None
                else None
            ),
            prompt_logprobs=(
                list(seq.prompt_logprobs)
                if seq.prompt_logprobs is not None
                else None
            ),
            first_scheduled_time=m.first_scheduled_time,
            first_token_time=m.first_token_time,
            last_token_time=m.last_token_time,
            time_in_queue=m.time_in_queue,
            digests=(
                chain_digests(token_ids, bs, seq.lora_name, pages)
                if pages
                else []
            ),
            pages=pages,
            t0=t0,
            request_class=seq.request_class,
        )
        tier.stage_checkpoint(ckpt)
        self.recorder.record(
            "checkpoint", seq.request_id, step=self.step_counter,
            trace_id=seq.trace_id, output_tokens=seq.num_output_tokens,
            pages=pages,
        )
        return ckpt

    def resume_request(self, ckpt, path: str = "local") -> None:  # noqa: ANN001
        """Re-enter one checkpointed mid-decode request
        (docs/RECOVERY.md): rebuild its ``Sequence`` — emitted tokens,
        sampler seed, detokenizer/FSM state replayed, streaming
        bookkeeping restored so nothing re-emits — and hand it to the
        scheduler as a preemption-resume-shaped admission.  The kv gate
        then promotes the checkpointed pages from the host tier and the
        uncovered tail recomputes, so decode continues token-identically
        (the sampler folds the per-request POSITION into the per-request
        key, so the draw stream is scheduling-independent).

        ``path`` labels the flight-recorder event and metrics: 'local'
        (into the rebuilt replica) or 'cross_replica' (onto a healthy
        dp sibling before the rebuild).
        """
        rid = ckpt.request_id
        if rid in self._seqs:
            raise ValueError(f"duplicate request_id {rid!r}")
        params = ckpt.params
        seq = Sequence(
            rid,
            ckpt.prompt,
            list(ckpt.prompt_token_ids),
            params,
            arrival_time=ckpt.arrival_time,
            fallback_seed=ckpt.fallback_seed,
            lora_name=ckpt.lora_name,
        )
        seq.resumed = True
        seq.trace_id = ckpt.trace_id
        seq.tenant_id = ckpt.tenant_id
        seq.deadline = ckpt.deadline
        seq.request_class = getattr(ckpt, "request_class", "chat")
        seq.output_token_ids = list(ckpt.output_token_ids)
        if ckpt.output_logprobs is not None:
            seq.output_logprobs = list(ckpt.output_logprobs)
        if ckpt.prompt_logprobs is not None:
            seq.prompt_logprobs = list(ckpt.prompt_logprobs)
        m = seq.metrics
        # timing restore: a resumed request is NOT a new arrival — TTFT
        # was observed in its first life and must not re-observe
        m.first_scheduled_time = ckpt.first_scheduled_time
        m.first_token_time = ckpt.first_token_time
        m.last_token_time = ckpt.last_token_time
        m.time_in_queue = ckpt.time_in_queue
        m.events.append(("resumed", time.time_ns()))
        self._prepare_admission(seq)
        if seq.fsm is not None:
            state = seq.fsm.init_state
            for tok in seq.output_token_ids:
                # replay, don't carry: state ids are private to THIS
                # compile of the FSM
                state = seq.fsm.next_state(state, tok)
            seq.fsm_state = state
        if seq.output_token_ids:
            # deterministic replay: output_text lands exactly where the
            # dead engine left it, so DELTA offsets below stay valid
            seq.detokenizer.append(list(seq.output_token_ids))
        seq.stop_scan_pos = ckpt.stop_scan_pos
        seq._emitted_token_len = ckpt.emitted_token_len  # noqa: SLF001
        seq._emitted_text_len = ckpt.emitted_text_len  # noqa: SLF001
        self._commit_admission(seq)
        self.recorder.record(
            "resume", rid, step=self.step_counter, trace_id=ckpt.trace_id,
            output_tokens=len(seq.output_token_ids), path=path,
        )

    # --------------------------------------------- prefill→decode handoff

    def _stage_handoffs(self, plan) -> None:  # noqa: ANN001
        """Prefill-role commit hook (docs/SCALING.md "Disaggregated
        roles"): every sequence this commit left MID-DECODE — its
        prefill finished and its first token sampled (and, for DELTA
        streams, already emitted by ``_process_sampled``) — leaves this
        replica NOW, as a staged decode checkpoint the async layer
        resumes on a decode-capable replica.  Decode plans are scanned
        too: the only legitimately decoding rows here are precompile
        warmups (exempt) and requests a role-degraded resume parked on
        this replica — the latter must bounce back off rather than
        decode a prefill replica's bucket away."""
        if isinstance(plan, RaggedPlan):
            seqs = [item.seq for item in plan.items]
        elif isinstance(plan, PrefillPlan):
            seqs = [plan.seq]
        else:
            seqs = list(plan.seqs)
        for seq in seqs:
            if (
                seq.is_finished
                or seq.num_output_tokens < 1
                or seq.request_id.startswith("__warmup")
                or self._seqs.get(seq.request_id) is not seq
            ):
                continue
            if seq.status != SequenceStatus.RUNNING:
                # a resumed request MID-CHUNK through its recompute tail
                # (status WAITING, pages held, queued for the next
                # chunk): it carries output tokens from its first life
                # but has NOT finished prefill here — staging it now
                # would hand off a stale checkpoint while the scheduler
                # keeps (re)running it from the waiting queue, double-
                # executing the stream.  It stages at its final-chunk
                # commit, exactly like a fresh prompt.
                continue
            self._stage_handoff(seq)

    def _stage_handoff(self, seq: Sequence) -> None:
        """Capture one finished-prefill sequence for decode handoff:
        ``checkpoint_decode`` demotes its written pages into the
        fleet-shared host tier and stages the ``DecodeCheckpoint``
        (identity, sampler seed, stream offsets, digest-validated
        pages — the PR-10 record, verbatim); the sequence's device
        state is released immediately — the demotion gathers were
        ENQUEUED first, so the device reads its pages before any later
        program can overwrite them (the ``_tier_demote`` ordering
        contract).  A ``None`` checkpoint means the capture ladder
        failed (tier budget, gather failure); the async layer's drain
        turns that into a retryable ``HandoffError``."""
        ckpt = self.checkpoint_decode(seq)
        self.scheduler.finish(seq)
        self._seqs.pop(seq.request_id, None)
        self.lora_manager.unpin(seq.lora_name)
        self.pending_handoffs.append((seq.request_id, ckpt))
        self.recorder.record(
            "handoff_out", seq.request_id, step=self.step_counter,
            trace_id=seq.trace_id, staged=ckpt is not None,
            output_tokens=seq.num_output_tokens,
            pages=getattr(ckpt, "pages", 0),
        )

    # ------------------------------------------------------------- step loop

    def precompile(self, batch_widths: str = "all") -> int:
        """Boot-time shape warmup: drive dummy requests through every
        prefill bucket and decode batch-width bucket so production
        traffic never pays an XLA/Mosaic compile (first compiles run
        ~20-40s each on TPU; the persistent compilation cache then
        serves restarts).  Mirrors the TPU warmup the reference stack
        inherits from vLLM's TPU worker.

        ``batch_widths``: decode runs at ONE width (max_num_seqs);
        "all" additionally compiles the want_topn sampler variant and
        the full flat-bucket ladder, "max" keeps boot fast and lets
        rare variants compile as load ramps.

        Returns the number of warmup requests run.  Must be called
        before serving starts (asserts the engine is idle); leaves no
        residual state (all warmup requests run to completion).
        """
        if self.has_unfinished_requests():
            raise RuntimeError("precompile must run on an idle engine")
        sched = self.scheduler
        max_len = self.config.max_model_len
        # decode runs at ONE width (max_num_seqs) — the per-width
        # bucket ladder is retired, so one pass warms it
        widths = [sched.config.max_num_seqs]
        # "all" also compiles the want_topn=True decode variant (static
        # argnum: flipping it at serving time is a fresh full compile)
        topn_variants = [False, True] if batch_widths == "all" else [False]
        # two full fused waves: the first compiles the production
        # num_decode_steps program, the second is dispatched CHAINED so
        # the async loop's separately-jitted _chained_decode_fn compiles
        # at the same (width, steps) shape
        steps = sched.config.num_decode_steps
        total = 0
        # solo-prefill buckets whose program ACTUALLY compiled: recorded
        # by _precompile_drain from the plans it dispatched (ADVICE r5:
        # recording at add_request time was optimistic — _extend_pack
        # swallows co-admitted warmup prompts into a PACKED dispatch, a
        # different entry point, leaving the solo shape cold and the
        # first real solo prompt at that bucket paying a serving-time
        # compile)
        covered: set[int] = set()

        def warm_len(bucket: int, headroom: int = 0) -> int:
            return max(1, min(bucket, max_len - (headroom or 2 * steps) - 2))

        for width in widths:
            for want_topn in topn_variants:
                for i in range(width):
                    bucket = sched.config.prefill_buckets[
                        i % len(sched.config.prefill_buckets)
                    ]
                    self.add_request(
                        f"__warmup_{width}_{want_topn}_{i}",
                        None,
                        SamplingParams(
                            temperature=0.0, max_tokens=2 * steps + 1,
                            ignore_eos=True,
                            logprobs=1 if want_topn else None,
                        ),
                        prompt_token_ids=[1] * warm_len(bucket),
                    )
                    total += 1
                self._precompile_drain(width, covered)
        if sched.ragged:
            # ragged shape set: the mixed step compiles per FLAT-LENGTH
            # bucket (scheduler.ragged_buckets), regardless of batch
            # mix.  Fill each reachable bucket with exactly enough
            # whole/chunked prompts — the floor-bucket + slice-to-fit
            # planner then dispatches at precisely that bucket.
            budget = min(sched.chunk_budget, sched.ragged_buckets[-1])
            for bucket in sched.ragged_buckets:
                if bucket in covered or bucket > budget:
                    continue
                # prompts of min(bucket, usable max_len): desired lands
                # in [bucket, bucket + warm) so the floor-bucket planner
                # dispatches at exactly this bucket
                warm = min(warm_len(max_len, headroom=1), bucket)
                n = -(-bucket // warm)
                if n > sched.config.max_num_seqs:
                    continue  # unreachable: admission is slot-bounded
                for i in range(n):
                    self.add_request(
                        f"__warmup_ragged_{bucket}_{i}",
                        None,
                        SamplingParams(temperature=0.0, max_tokens=1,
                                       ignore_eos=True),
                        prompt_token_ids=[1] * warm,
                    )
                    total += 1
                self._precompile_drain(n, covered)
            total += self._precompile_ragged_tail(covered)
        else:
            # prefill compiles key on the BUCKET, not the batch width:
            # any bucket whose solo shape no dispatched plan covered
            # (packed admission, narrow batches, long bucket lists)
            # gets a solo pass — one request at a time, so _extend_pack
            # has nothing to pack it with and the solo program truly
            # compiles
            for bucket in sched.config.prefill_buckets:
                if bucket in covered or bucket >= max_len:
                    continue
                self.add_request(
                    f"__warmup_bucket_{bucket}",
                    None,
                    SamplingParams(temperature=0.0, max_tokens=1,
                                   ignore_eos=True),
                    prompt_token_ids=[1] * warm_len(bucket, headroom=1),
                )
                total += 1
                self._precompile_drain(1, covered)
        logger.info(
            "precompile: %d warmup requests across %d batch widths, "
            "%d prefill buckets (topn variants: %s, chained: yes)",
            total, len(widths), len(covered), topn_variants,
        )
        return total

    def _precompile_drain(
        self, width: int, covered: Optional[set[int]] = None
    ) -> None:
        """Run the warmup batch to completion, dispatching the FIRST
        full-batch decode wave CHAINED (mirroring the async loop's
        plan_chained_step -> dispatch_chained_step -> commit order,
        free-epoch discipline included) so the chained program compiles
        at the production (width, num_decode_steps) shape rather than
        on the first live chained wave.

        ``covered`` (when given) collects the SOLO prefill buckets this
        drain actually dispatched — the ground truth precompile() needs
        to decide which buckets still want a solo pass (packed plans
        compile a different entry point and do not count).

        All prefills drain first (``prefill_only=True`` planning):
        organic interleaving would let early rows burn their max_tokens
        budget before the batch fills, making schedule_chained bail on
        the full-width wave (the projection needs >= 1 step of headroom
        on every row)."""

        def note_plan(plan) -> None:  # noqa: ANN001
            if covered is None:
                return
            if isinstance(plan, PrefillPlan):
                covered.add(plan.bucket_len)
            elif isinstance(plan, RaggedPlan):
                covered.add(plan.token_bucket)

        guard = 0
        while True:
            guard += 1
            if guard > 50 * width + 500:  # pragma: no cover
                raise RuntimeError("precompile prefill did not converge")
            # the prefill/decode anti-starvation interleave
            # (scheduler._last_was_prefill) returns None after every
            # admission; there is nothing to starve during warmup, so
            # clear it — all prompts must be resident before the first
            # decode or early rows burn their budget pre-full-width
            self.scheduler._last_was_prefill = False
            outputs, plan, prepared = self.plan_step(prefill_only=True)
            if plan is None:
                break
            note_plan(plan)
            self.commit_step(
                plan,
                self.wait_step(
                    plan, prepared, self.dispatch_step(plan, prepared)
                ),
                prepared,
            )
        chained_done = False
        guard = 0
        while self.has_unfinished_requests():
            guard += 1
            if guard > 200 * width + 2000:  # pragma: no cover
                raise RuntimeError("precompile did not converge")
            outputs, plan, prepared = self.plan_step()
            if plan is None:
                continue
            note_plan(plan)
            handle = self.dispatch_step(plan, prepared)
            chained = None
            if not chained_done:
                chained = self.plan_chained_step(plan, prepared)
            if chained is None:
                self.commit_step(
                    plan, self.wait_step(plan, prepared, handle), prepared
                )
                continue
            c_plan, c_prep = chained
            self.begin_free_epoch()
            try:
                c_handle = self.dispatch_chained_step(
                    c_plan, c_prep, handle
                )
                self.commit_step(
                    plan, self.wait_step(plan, prepared, handle), prepared
                )
                c_result = self.wait_step(c_plan, c_prep, c_handle)
            finally:
                # chained wave retired — or died with the warmup: a
                # supervised re-warm failure is retried, and an epoch
                # left open here would quarantine every later free on
                # the retrying engine (tpulint TPL501)
                self.flush_free_epoch()
            self.commit_step(c_plan, c_result, c_prep)
            chained_done = True

    def _precompile_ragged_tail(self, covered: set[int]) -> int:
        """Warm the flat-length buckets only DECODE-HEAVY mixed steps
        reach (--attention-backend=ragged).  Prompt warmups top out at
        the chunk budget per dispatch, but a serving step with ``base``
        running rows plans ``max(floor_bucket(base + take),
        _ragged_bucket(base + 1))`` — past the chunk budget whenever
        the running batch is large.  Park just enough one-token rows in
        decode, then ride one filler prompt with them: the planner
        dispatches at exactly the target bucket.  Best-effort — buckets
        this config cannot reach are skipped silently, and a KV pool
        too small for the parked rows downgrades to a serving-time
        compile (logged)."""
        sched = self.scheduler
        s_max = sched.config.max_num_seqs
        chunk = sched.chunk_budget
        block_size = self.config.cache_config.block_size
        total = 0
        prev = 0
        for bucket in sched.ragged_buckets:
            if bucket in covered:
                prev = bucket
                continue
            if prev and prev < s_max:
                # base past the previous ladder entry lifts
                # _ragged_bucket(base + 1) to this bucket on its own
                # (prev == s_max would park every slot and leave no
                # room to admit the filler prompt)
                base_rows, filler_len = prev, 1
            elif (
                1 <= bucket - chunk <= s_max
                and chunk <= self.config.max_model_len - 2
            ):
                # floor-bucket route: base + a full chunk lands exactly
                # (needs a legal chunk-length filler prompt)
                base_rows, filler_len = bucket - chunk, chunk
            else:
                prev = bucket
                continue  # no (base, chunk) mix reaches this bucket
            prev = bucket
            # one-token prompts admit whole rows even when the plan has
            # a single token of space left (no intra-prompt crawl); each
            # parked row decodes once per plan while the rest admit
            life = -(-base_rows // chunk) + 12
            pages = base_rows * (-(-(1 + life) // block_size))
            if pages > int(0.9 * sched.allocator.num_blocks):
                logger.warning(
                    "precompile: skipping ragged bucket %d — %d warm "
                    "rows need ~%d KV pages, pool has %d; the first "
                    "decode-heavy step there compiles at serving time",
                    bucket, base_rows, pages, sched.allocator.num_blocks,
                )
                continue
            for i in range(base_rows):
                self.add_request(
                    f"__warmup_mix_{bucket}_{i}", None,
                    SamplingParams(temperature=0.0, max_tokens=life,
                                   ignore_eos=True),
                    prompt_token_ids=[3],
                )
                total += 1
            guard = 0
            while sched.waiting:
                guard += 1
                if guard > 50 * base_rows + 500:  # pragma: no cover
                    raise RuntimeError(
                        "precompile mixed warm did not converge"
                    )
                sched._last_was_prefill = False
                self.step()
            self.add_request(
                f"__warmup_mix_{bucket}_filler", None,
                SamplingParams(temperature=0.0, max_tokens=1,
                               ignore_eos=True),
                prompt_token_ids=[3] + [1] * (filler_len - 1),
            )
            total += 1
            outputs, plan, prepared = self.plan_step()
            if isinstance(plan, RaggedPlan):
                covered.add(plan.token_bucket)
            if plan is not None:
                self.commit_step(
                    plan, self.execute_step(plan, prepared), prepared
                )
            if bucket not in covered:  # pragma: no cover
                logger.warning(
                    "precompile: mixed warm missed ragged bucket %d "
                    "(planned %s)", bucket,
                    type(plan).__name__ if plan is not None else None,
                )
            guard = 0
            while self.has_unfinished_requests():
                guard += 1
                if guard > 50 * base_rows + 2000:  # pragma: no cover
                    raise RuntimeError(
                        "precompile mixed drain did not converge"
                    )
                self.step()
        return total

    def step(self) -> list[RequestOutput]:
        """Run one device step; return outputs due for emission.

        Composes the three phases below; the async engine calls them
        separately so the engine lock is held only for the (fast) host
        phases and add_request/abort can land during the device dispatch.
        """
        outputs, plan, prepared = self.plan_step()
        if plan is None:
            return outputs
        result = self.execute_step(plan, prepared)
        return outputs + self.commit_step(plan, result, prepared)

    def plan_step(self, prefill_only: bool = False):
        """Phase 1 (host, engine lock held): drain scheduler-finished
        requests, pick the next plan, snapshot its dispatch inputs.

        ``prefill_only``: the async loop sets this while a dispatch is
        still in flight — admissions are independent of in-flight results
        and may be enqueued behind them, whereas a decode plan depends on
        the pending commit (tokens, page frees) and must wait.
        """
        failpoints.fire("core.plan_step")
        _st_enter = time.perf_counter()  # steptime: plan-phase origin
        outputs: list[RequestOutput] = []
        for seq in self.scheduler.newly_finished:
            self._seqs.pop(seq.request_id, None)
            self.lora_manager.unpin(seq.lora_name)
            seq.metrics.finished_time = time.time()
            self.recorder.record(
                "finish", seq.request_id, step=self.step_counter,
                trace_id=seq.trace_id, reason=seq.finish_reason,
                rejected=True,
            )
            outputs.append(seq.to_request_output())
        self.scheduler.newly_finished.clear()

        _drain_s = 0.0
        if not prefill_only and self.scheduler.swap_out_fn is not None:
            # prefill_only means a dispatch is in flight — restoring
            # would rebind runner.caches under it (runner.restore_kv)
            _t = time.perf_counter()
            self._drain_swap_ins()
            _drain_s += time.perf_counter() - _t
        if not prefill_only and self.kv_tier is not None:
            # same clean-boundary contract: the promotion scatter also
            # rebinds runner.caches (runner.restore_kv_block)
            _t = time.perf_counter()
            self._drain_promotions()
            _drain_s += time.perf_counter() - _t
        self.runner.sync_lora(self.lora_manager)
        plan = self.scheduler.schedule(prefill_only=prefill_only)
        _st_sched = time.perf_counter()
        if plan is None:
            return outputs, None, None

        if isinstance(plan, RaggedPlan):
            now = time.time()
            for item in plan.items:
                m = item.seq.metrics
                if m.first_scheduled_time is None:
                    m.first_scheduled_time = now
                    m.time_in_queue = now - m.arrival_time
            prepared = self.runner.prepare_ragged(plan)
        elif isinstance(plan, PrefillPlan):
            seq = plan.seq
            if seq.metrics.first_scheduled_time is None:
                now = time.time()
                seq.metrics.first_scheduled_time = now
                seq.metrics.time_in_queue = now - seq.metrics.arrival_time
            prepared = self.runner.prepare_prefill(plan)
        else:
            prepared = self.runner.prepare_decode(plan)
        self._observe_plan(plan, prepared)
        self._record_dispatch(plan)
        self.steptime.stamp_plan(
            prepared, t_enter=_st_enter, t_sched=_st_sched,
            drain_s=_drain_s,
        )
        return outputs, plan, prepared

    def _record_dispatch(self, plan) -> None:  # noqa: ANN001
        """One recorder entry per dispatch (per prompt for prefills, so
        ``events_for(request_id)`` sees every wave that touched it;
        batch-level for decode — per-request decode cadence is the
        ``decode_progress`` marker in ``_process_sampled``)."""
        self.step_counter += 1
        step = self.step_counter
        if isinstance(plan, RaggedPlan):
            for item in plan.items:
                self.recorder.record(
                    "ragged_step", item.seq.request_id, step=step,
                    trace_id=item.seq.trace_id,
                    bucket=plan.token_bucket,
                    tokens=len(item.token_ids),
                    start_pos=item.start_pos,
                    decode=item.is_decode,
                    is_final=item.is_final,
                    # the verify phase of the ragged step: this item is
                    # a speculative verify span (docs/OBSERVABILITY.md)
                    verify=item.spec_width > 0,
                )
            return
        if isinstance(plan, PrefillPlan):
            self.recorder.record(
                "prefill", plan.seq.request_id, step=step,
                trace_id=plan.seq.trace_id, bucket=plan.bucket_len,
                start_pos=plan.start_pos, tokens=len(plan.token_ids),
                is_final=plan.is_final,
            )
        else:
            self.recorder.record(
                "decode", step=step, num_seqs=len(plan.seqs),
                batch_bucket=plan.batch_bucket, num_steps=plan.num_steps,
            )

    def _observe_plan(self, plan, prepared) -> None:
        """Step-level telemetry (metrics.py): batch occupancy / padding
        waste gauges for this dispatch's shape, plus the plan→commit
        timestamp the commit phase turns into a step-duration sample."""
        try:
            if isinstance(plan, RaggedPlan):
                metrics.observe_ragged_plan(
                    real_tokens=plan.total_tokens,
                    bucket=plan.token_bucket,
                    num_prefill=sum(
                        1 for i in plan.items if not i.is_decode
                    ),
                    num_decode=sum(1 for i in plan.items if i.is_decode),
                )
            elif isinstance(plan, PrefillPlan):
                metrics.observe_prefill_plan(
                    real_tokens=len(plan.token_ids),
                    bucket=plan.bucket_len,
                    num_prompts=1,
                )
            else:
                metrics.observe_decode_plan(
                    num_seqs=len(plan.seqs),
                    batch_bucket=plan.batch_bucket,
                    num_steps=plan.num_steps,
                    replica=self.replica_index,
                )
        except Exception:  # pragma: no cover — metrics are best-effort
            logger.debug("step metric observation failed", exc_info=True)
        if prepared is not None:
            prepared._obs_plan_t0 = time.perf_counter()  # noqa: SLF001

    def execute_step(self, plan, prepared):
        """Phase 2 (device, lock-free): runs only against the snapshot and
        runner-owned device state — never reads scheduler structures."""
        self.steptime.begin_wait(prepared)
        if isinstance(plan, RaggedPlan):
            result = self.runner.execute_ragged(prepared)
        elif isinstance(plan, PrefillPlan):
            result = self.runner.execute_prefill(prepared)
        else:
            result = self.runner.execute_decode(prepared)
        self.steptime.end_wait(prepared)
        return result

    def _stamp_dispatched(self, prepared, handle) -> None:  # noqa: ANN001
        """steptime: close the dispatch window, noting whether the
        runner deferred the device work to wait (SYNC_DISPATCH) and any
        XLA compile in flight when this step was enqueued."""
        from vllm_tgis_adapter_tpu import compile_tracker

        inflight = compile_tracker.inflight_dispatch()
        self.steptime.end_dispatch(
            prepared,
            sync=handle is SYNC_DISPATCH,
            compile_fn=inflight[0] if inflight is not None else None,
        )

    def dispatch_step(self, plan, prepared):
        """Phase 2a (lock-free): enqueue the device work without blocking
        on results (JAX async dispatch).  Pair with ``wait_step``; the
        async engine plans and dispatches the NEXT step between the two,
        so host-side prep overlaps device execution."""
        failpoints.fire("core.dispatch_step")  # worker thread: hang-capable
        self.steptime.begin_dispatch(prepared)
        if isinstance(plan, RaggedPlan):
            handle = self.runner.dispatch_ragged(prepared)
        elif isinstance(plan, PrefillPlan):
            handle = self.runner.dispatch_prefill(prepared)
        else:
            handle = self.runner.dispatch_decode(prepared)
        self._stamp_dispatched(prepared, handle)
        return handle

    def wait_step(self, plan, prepared, handle):
        """Phase 2b (lock-free, blocking): pull the dispatched step's
        results to host."""
        failpoints.fire("core.wait_step")  # worker thread: hang-capable
        self.steptime.begin_wait(prepared)
        if isinstance(plan, RaggedPlan):
            result = self.runner.wait_ragged(prepared, handle)
        elif isinstance(plan, PrefillPlan):
            result = self.runner.wait_prefill(prepared, handle)
        else:
            result = self.runner.wait_decode(prepared, handle)
        self.steptime.end_wait(prepared)
        return result

    # --------------------------------------------------- chained decode waves

    def plan_chained_step(self, prev_plan, prev_prepared):
        """Phase 1' (host, engine lock held): plan the SUCCESSOR decode
        wave of an in-flight plain decode dispatch — projections assume
        full step consumption; token feedback stays on device
        (scheduler.schedule_chained / runner.prepare_chained_decode).
        Returns (plan, prepared) or None when chaining is not safe."""
        if not self.config.scheduler_config.enable_chained_decode:
            return None
        if not isinstance(prev_plan, DecodePlan):
            return None
        _st_enter = time.perf_counter()
        plan = self.scheduler.schedule_chained(prev_plan)
        if plan is None:
            return None
        _st_sched = time.perf_counter()
        prepared = self.runner.prepare_chained_decode(plan, prev_prepared)
        self._observe_plan(plan, prepared)
        self._record_dispatch(plan)
        self.steptime.stamp_plan(
            prepared, t_enter=_st_enter, t_sched=_st_sched, chained=True,
        )
        return plan, prepared

    def dispatch_chained_step(self, plan, prepared, prev_handle):  # noqa: ARG002
        """Phase 2a' (lock-free): enqueue the successor wave behind the
        in-flight one."""
        self.steptime.begin_dispatch(prepared)
        handle = self.runner.dispatch_chained_decode(prepared, prev_handle)
        self._stamp_dispatched(prepared, handle)
        return handle

    def begin_free_epoch(self) -> None:
        self.scheduler.allocator.begin_free_epoch()

    def flush_free_epoch(self) -> None:
        self.scheduler.allocator.flush_free_epoch()

    def flush_all_free_epochs(self) -> None:
        """Step-loop teardown: nothing can be in flight any more, so any
        epochs left open (loop died between a chained dispatch and its
        commit) release their quarantined pages."""
        self.scheduler.allocator.flush_all_free_epochs()

    def commit_step(self, plan, result, prepared=None) -> list[RequestOutput]:
        """Phase 3 (host, engine lock held): fold sampled tokens back into
        sequences; requests aborted mid-dispatch are skipped here.  On a
        'prefill'-role replica (docs/SCALING.md "Disaggregated roles"),
        sequences left mid-decode by this commit — their first token just
        sampled — are then staged for handoff to a decode replica."""
        outputs = self._commit_inner(plan, result, prepared)
        if self.replica_role == "prefill":
            self._stage_handoffs(plan)
        # step-boundary invariant sanitizer (TGIS_TPU_SANITIZE=1, zero
        # cost off): every commit leaves the allocator/arena/tier/pool
        # accounting closed, or we fail HERE rather than serving from
        # corrupt state (engine/sanitizer.py, docs/STATIC_ANALYSIS.md)
        sanitizer.maybe_check(self)
        self._finish_step_record(plan, prepared)
        return outputs

    def _finish_step_record(self, plan, prepared) -> None:  # noqa: ANN001
        """Commit boundary: finalize this dispatch's StepRecord
        (telemetry/steptime.py) with the plan's shape facts."""
        if prepared is None or plan is None:
            return
        if isinstance(plan, RaggedPlan):
            kind = "ragged"
            tokens = plan.total_tokens
            bucket = plan.token_bucket
            fill = tokens / bucket if bucket else 0.0
        elif isinstance(plan, PrefillPlan):
            kind = "solo"
            tokens = len(plan.token_ids)
            fill = tokens / plan.bucket_len if plan.bucket_len else 0.0
        else:
            kind = "decode-wave"
            tokens = len(plan.seqs) * plan.num_steps
            fill = (
                len(plan.seqs) / plan.batch_bucket
                if plan.batch_bucket
                else 0.0
            )
        self.steptime.finish(
            prepared, step=self.step_counter,
            replica=self.replica_index, kind=kind, tokens=tokens,
            fill_ratio=fill,
        )

    def _commit_inner(self, plan, result, prepared=None) -> list[RequestOutput]:
        failpoints.fire("core.commit_step")
        t0 = getattr(prepared, "_obs_plan_t0", None)
        if t0 is not None:
            duration = time.perf_counter() - t0
            rep = str(self.replica_index)
            role = self.replica_role
            if isinstance(plan, DecodePlan):
                metrics.decode_step_seconds.labels(
                    replica=rep, replica_role=role
                ).observe(duration)
            else:
                metrics.prefill_step_seconds.labels(
                    replica=rep, replica_role=role
                ).observe(duration)
        if isinstance(plan, RaggedPlan):
            seqs, toks = [], []
            spec_ran = prepared is not None and getattr(
                prepared, "spec_ran", False
            )
            for item, tok_list in zip(plan.items, result):
                seq = item.seq
                if seq.is_finished:
                    continue  # aborted while the ragged dispatch ran
                if item.is_final and not item.is_decode:
                    # the prompt's K/V is now fully resident: publish
                    # its pages for prefix reuse (device cache + host
                    # tier demotion)
                    self._register_prefix(seq)
                if tok_list is None:
                    continue  # mid-prompt chunk: nothing emitted yet
                seqs.append(seq)
                toks.append(tok_list)
                if (
                    spec_ran
                    and item.spec_width > 0
                    and self.ledger is not None
                ):
                    # per-request speculative attribution: the row
                    # proposed spec_width drafts and consumed
                    # len(tok_list) tokens, of which all but the bonus
                    # token were accepted drafts
                    self.ledger.note_spec(
                        seq.request_id,
                        item.spec_width,
                        max(0, len(tok_list) - 1),
                    )
            outputs = self._process_sampled(seqs, toks)
            if spec_ran:
                for item in plan.items:
                    if item.spec_width > 0 and not item.seq.is_finished:
                        # propose wrote draft K/V through the last
                        # consumed token's predecessor; everything
                        # beyond is stale-by-design (next catch-up /
                        # propose re-inputs the corrected token)
                        item.seq.draft_pos = item.seq.num_tokens - 1
            return outputs
        if isinstance(plan, PrefillPlan):
            seq = plan.seq
            sampled, prompt_info = result
            # draft-cache accounting: this chunk was mirrored into the
            # draft only if it extends the draft's contiguous prefix
            # (prefix-cache-adopted regions are target-only and get
            # re-run through the draft by the catch-up path)
            if (
                not seq.is_finished
                and prepared is not None
                and getattr(prepared, "spec_eligible", False)
                and seq.draft_pos == plan.start_pos
            ):
                seq.draft_pos = plan.start_pos + len(plan.token_ids)
            if seq.is_finished:
                return []  # aborted while the dispatch was in flight
            if (
                seq.params.prompt_logprobs is not None
                and seq.prompt_logprobs is None
                and plan.start_pos == 0
            ):
                # the table always exists once prefill ran — a 1-token
                # prompt has zero computable rows but still reports
                # [None] (position 0 never has a logprob)
                seq.prompt_logprobs = [None]
            if prompt_info is not None:
                # chunked prompt-logprobs: each chunk appends its rows
                self._append_prompt_logprobs(
                    seq, prompt_info, plan.start_pos
                )
            if sampled is None:
                return []  # mid-prompt chunk: nothing emitted yet
            # the prompt's K/V is now fully resident: publish its full
            # pages for prefix reuse (device cache + host tier demotion)
            self._register_prefix(seq)
            return self._process_sampled([seq], [[sampled]])
        return self._process_sampled(plan.seqs, result)

    # -------------------------------------------------------------- internal

    def _process_sampled(
        self, seqs: list[Sequence], sampled: list[list[SampledToken]]
    ) -> list[RequestOutput]:
        """Consume each row's sampled tokens (one per fused device step).

        A row that finishes (EOS / stop string / length) mid-list simply
        discards its remaining speculatively decoded tokens — their KV
        writes targeted pages the sequence owned, which are freed with it.
        """
        now = time.time()
        outputs = []
        for seq, toks in zip(seqs, sampled):
            if seq.is_finished:
                continue  # aborted mid-step
            # per-token latency telemetry: a fused wave commits all its
            # tokens with one host timestamp, so the wave's gap since the
            # previous commit is spread evenly over its tokens — sample
            # count stays the token count and the histogram sum stays the
            # true wall time (metrics.inter_token_seconds doc)
            first_wave = seq.metrics.first_token_time is None
            if first_wave:
                ttft = max(0.0, now - seq.metrics.arrival_time)
                metrics.ttft_seconds.observe(ttft)
                if (
                    self.slo is not None
                    and not seq.request_id.startswith("__warmup")
                ):
                    # SLO feed shares the histogram's observation point
                    # (telemetry/slo.py); resumed requests keep their
                    # restored first_token_time, so TTFT never
                    # re-observes across a migration
                    self.slo.observe_ttft(seq.request_class, ttft)
            prev_commit = seq.metrics.last_token_time
            consumed = 0
            for tok in toks:
                seq.output_token_ids.append(tok.token_id)
                consumed += 1
                if seq.fsm is not None:
                    seq.fsm_state = seq.fsm.next_state(
                        seq.fsm_state, tok.token_id
                    )
                if seq.metrics.first_token_time is None:
                    seq.metrics.first_token_time = now
                seq.metrics.last_token_time = now
                detok_t0 = time.perf_counter()
                seq.detokenizer.append([tok.token_id])
                seq.metrics.detokenize_time += (
                    time.perf_counter() - detok_t0
                )
                if seq.output_logprobs is not None:
                    seq.output_logprobs.append(
                        self._build_logprob_dict(seq, tok)
                    )
                self._maybe_finish(seq, tok.token_id)
                if seq.is_finished:
                    seq.metrics.finished_time = now
                    self.scheduler.finish(seq)
                    self._seqs.pop(seq.request_id, None)
                    self.lora_manager.unpin(seq.lora_name)
                    self.recorder.record(
                        "finish", seq.request_id, step=self.step_counter,
                        trace_id=seq.trace_id, reason=seq.finish_reason,
                        output_tokens=seq.num_output_tokens,
                    )
                    outputs.append(seq.to_request_output())
                    break
                if seq.num_output_tokens % DECODE_PROGRESS_EVERY == 0:
                    # bounded per-request decode cadence marker: one ring
                    # entry per N tokens, not per token
                    self.recorder.record(
                        "decode_progress", seq.request_id,
                        step=self.step_counter, trace_id=seq.trace_id,
                        output_tokens=seq.num_output_tokens,
                    )
                if seq.params.output_kind != RequestOutputKind.FINAL_ONLY:
                    # DELTA with an empty text delta still carries the token
                    outputs.append(seq.to_request_output())
            if not first_wave and prev_commit is not None and consumed:
                itl = max(0.0, now - prev_commit) / consumed
                for _ in range(consumed):
                    metrics.inter_token_seconds.observe(itl)
                if (
                    self.slo is not None
                    and not seq.request_id.startswith("__warmup")
                ):
                    for _ in range(consumed):
                        self.slo.observe_itl(seq.request_class, itl)
        return outputs

    def _maybe_finish(self, seq: Sequence, token_id: int) -> None:
        params = seq.params
        eos = self.config.model_config.eos_token_id
        if not params.ignore_eos and token_id == eos:
            seq.status = SequenceStatus.FINISHED_STOPPED
            seq.stop_reason = None
            return
        if params.stop:
            text = seq.output_text
            best: Optional[tuple[int, str]] = None
            # scan only the tail that new text could have completed: every
            # char before stop_scan_pos was already cleared on an earlier
            # token, so a first match can only start within len(s)-1 chars
            # of the old frontier (keeps the per-token cost O(delta), not
            # O(total output) — the earliest-match result is unchanged)
            frontier = seq.stop_scan_pos
            for s in params.stop:
                idx = text.find(s, max(0, frontier - len(s) + 1))
                if idx != -1 and (best is None or idx < best[0]):
                    best = (idx, s)
            seq.stop_scan_pos = len(text)
            if best is not None:
                idx, s = best
                seq.status = SequenceStatus.FINISHED_STOPPED
                seq.stop_reason = s
                end = idx + len(s) if params.include_stop_str_in_output else idx
                seq.detokenizer.output_text = text[:end]
                return
        max_tokens = params.max_tokens
        if max_tokens is not None and seq.num_output_tokens >= max_tokens:
            seq.status = SequenceStatus.FINISHED_LENGTH
            return
        if seq.num_tokens >= self.config.max_model_len:
            seq.status = SequenceStatus.FINISHED_LENGTH

    def _decode_token_text(self, token_id: int) -> str:
        return self.tokenizer.convert_ids_to_tokens(token_id)

    def _build_logprob_dict(
        self, seq: Sequence, tok: SampledToken
    ) -> dict[int, Logprob]:
        """{token_id: Logprob} for the chosen token + requested top-N."""
        n = seq.params.logprobs or 0
        entry: dict[int, Logprob] = {}
        for i in range(min(n, len(tok.topn_ids))):
            tid = tok.topn_ids[i]
            entry[tid] = Logprob(
                logprob=tok.topn_logprobs[i],
                rank=i + 1,
                decoded_token=self._decode_token_text(tid),
            )
        if tok.token_id not in entry:
            entry[tok.token_id] = Logprob(
                logprob=tok.logprob,
                rank=tok.rank,
                decoded_token=self._decode_token_text(tok.token_id),
            )
        return entry

    def _append_prompt_logprobs(
        self, seq: Sequence, info: PromptLogprobInfo, start_pos: int
    ) -> None:
        """Fold one (chunk's) prompt-logprob rows into the sequence's
        table.  Row i describes position ``start_pos + i + 1``; chunks
        commit in order, so appends only happen when the table's length
        is exactly the chunk's start — a preemption-resume re-running
        chunks over an already-recorded span is a no-op."""
        if seq.prompt_logprobs is None:
            seq.prompt_logprobs = [None]  # position 0 has no logprob
        if len(seq.prompt_logprobs) != start_pos + 1:
            return
        n = seq.params.prompt_logprobs or 0
        for i in range(len(info.logprobs)):
            pos = start_pos + i + 1
            if pos >= len(seq.prompt_token_ids):
                break
            token_id = seq.prompt_token_ids[pos]
            entry: dict[int, Logprob] = {}
            for j in range(min(n, len(info.topn_ids[i]))):
                tid = info.topn_ids[i][j]
                entry[tid] = Logprob(
                    logprob=info.topn_logprobs[i][j],
                    rank=j + 1,
                    decoded_token=self._decode_token_text(tid),
                )
            if token_id not in entry:
                entry[token_id] = Logprob(
                    logprob=info.logprobs[i],
                    rank=info.ranks[i],
                    decoded_token=self._decode_token_text(token_id),
                )
            seq.prompt_logprobs.append(entry)
