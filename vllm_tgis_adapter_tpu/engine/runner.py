"""Model runner: scheduler plans → jitted device programs → sampled tokens.

Owns the jit-compiled prefill/decode functions, the device-resident KV
caches, the seen-token matrix for repetition penalties, and the sampler
invocation.  All shapes flowing into jit are drawn from the scheduler's
buckets, so the compile count is bounded by
``len(prefill_buckets) + len(batch_buckets)`` (SURVEY.md §7 "XLA
recompilation discipline").
"""

from __future__ import annotations

import dataclasses
import functools
from typing import TYPE_CHECKING, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_tgis_adapter_tpu.compile_tracker import track_jit
from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod
from vllm_tgis_adapter_tpu.engine.sampler import TOPN_WIDTH, SamplingTensors
from vllm_tgis_adapter_tpu.logging import init_logger
from vllm_tgis_adapter_tpu.supervisor import failpoints

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import EngineConfig
    from vllm_tgis_adapter_tpu.engine.scheduler import DecodePlan, PrefillPlan

logger = init_logger(__name__)

#: dispatch/wait split sentinel: returned by a ``dispatch_*`` method when
#: the path cannot enqueue-only (speculative multi-phase verify, staged
#: pipeline runner) — the paired ``wait_*`` then runs the full execution.
SYNC_DISPATCH = object()

#: minimum Pallas work-schedule width per ragged dispatch: small mixed
#: batches all share one width instead of retracing the ragged step at
#: every distinct pow2(item count) (dead items are flag-0 no-op grid
#: steps whose repeated page index elides the DMA — cheap)
_RAGGED_WORK_FLOOR = 64


@dataclasses.dataclass
class SampledToken:
    """Host-side result for one sequence after one step."""

    token_id: int
    logprob: float
    rank: int
    topn_ids: list[int]
    topn_logprobs: list[float]


@dataclasses.dataclass
class PromptLogprobInfo:
    """Per-position prompt logprob table (position 0 has no entry)."""

    logprobs: list[float]  # [T-1] for positions 1..T-1
    ranks: list[int]
    topn_ids: list[list[int]]
    topn_logprobs: list[list[float]]

    @classmethod
    def from_packed(cls, packed_dev, n: int) -> "PromptLogprobInfo":
        """Unpack sampler.pack_prompt_logprob_parts — one device fetch
        for the whole prompt-logprob row table."""
        # tpulint: disable=TPL202(sanctioned sync: the ONE packed fetch per prompt-logprob table, called from the blocking wait_* half only)
        packed = np.asarray(packed_dev)[:n]  # [n, 2+2W]
        w = (packed.shape[-1] - 2) // 2
        return cls(
            logprobs=np.ascontiguousarray(
                packed[..., 0]).view(np.float32).tolist(),
            ranks=packed[..., 1].tolist(),
            topn_ids=packed[..., 2:2 + w].tolist(),
            topn_logprobs=np.ascontiguousarray(
                packed[..., 2 + w:]).view(np.float32).tolist(),
        )


@dataclasses.dataclass
class PreparedPrefill:
    """Host-built dispatch inputs for one prefill (chunk) step.

    Snapshotted from the sequence under the engine lock so the device
    dispatch can run lock-free (engine/async_llm.py step loop).
    """

    t: int  # real tokens in this chunk
    token_ids: "np.ndarray"  # [bucket]
    positions: "np.ndarray"  # [bucket] global positions
    slot_mapping: "np.ndarray"  # [bucket]
    start_pos: int
    is_final: bool
    block_table: "Optional[np.ndarray]"  # [max_blocks] when start_pos > 0
    logits_indices: "np.ndarray"
    want_prompt_lp: bool
    row_slot: int
    seen_tokens: "Optional[np.ndarray]"  # final chunks only
    tensors: Optional[SamplingTensors]  # final chunks only
    allowed_row: "Optional[np.ndarray]"  # FSM mask, final chunks only
    lora_slot: int
    # mirror this chunk into the draft cache (spec-eligible rows only —
    # ineligible rows would pay a draft forward they can never use)
    spec_eligible: bool = False
    # chunked prompt-logprobs: token each logits row predicts (-1 pads;
    # a chunk's last row targets the NEXT chunk's first token) and the
    # valid row count — positions past the prompt carry none
    lp_targets: "Optional[np.ndarray]" = None
    lp_rows: int = 0


@dataclasses.dataclass
class PreparedPackedPrefill:
    """Host-built dispatch inputs for one packed multi-prompt prefill.

    ``MAX_PACK`` fixed-width per-row arrays (segment starts, logits rows,
    sampler tensors) keep one compile shape per token bucket regardless
    of how many prompts were packed (engine/scheduler.py MAX_PACK).
    """

    bucket: int
    num_items: int  # real packed prompts (<= MAX_PACK)
    total_tokens: int  # real tokens across all segments
    token_ids: "np.ndarray"  # [bucket] concatenated prompts
    positions: "np.ndarray"  # [bucket] restarting at 0 per segment
    slot_mapping: "np.ndarray"  # [bucket]
    seg_starts: "np.ndarray"  # [MAX_PACK] flat start per segment (pad=bucket)
    logits_indices: "np.ndarray"  # [MAX_PACK] last-token row (pad=0)
    row_slots: "np.ndarray"  # [MAX_PACK] batch row per segment (pad=-1)
    seen_tokens: "np.ndarray"  # [MAX_PACK, P] prompt ids for seen seeding
    tensors: SamplingTensors  # MAX_PACK rows
    allowed_mask: "Optional[np.ndarray]"  # [MAX_PACK, V] FSM rows or None
    lora_slot: int  # shared by every packed prompt (scheduler invariant)


@dataclasses.dataclass
class PreparedRagged:
    """Host-built dispatch inputs for one unified ragged step
    (scheduler.RaggedPlan → ops/ragged_attention.py).

    The flat token axis concatenates every item's span (decode rows,
    then prefill chunks/prompts) and pads only to ``bucket``; the
    per-sequence descriptor arrays are fixed at ``max_num_seqs`` width
    so ONE compile per flat-length bucket serves every batch mix.
    """

    bucket: int
    total_tokens: int
    num_items: int
    token_ids: "np.ndarray"  # [bucket]
    positions: "np.ndarray"  # [bucket] global positions
    slot_mapping: "np.ndarray"  # [bucket] (-1 pads)
    seq_starts: "np.ndarray"  # [S_max+1] span starts (pads = bucket)
    pos_base: "np.ndarray"  # [S_max]
    block_tables: "np.ndarray"  # [S_max, max_blocks]
    logits_indices: "np.ndarray"  # [S_max] last-row per item (pad 0)
    row_slots: "np.ndarray"  # [S_max] batch row per SAMPLING item (-1)
    seed_slots: "np.ndarray"  # [S_max] rows to (re)seed seen (-1 skip)
    seed_tokens: "np.ndarray"  # [S_max, P] prompt ids for seeding
    tensors: SamplingTensors  # S_max rows
    allowed_mask: "Optional[np.ndarray]"  # [S_max, V] FSM rows or None
    lora_idx: "Optional[np.ndarray]"  # [bucket] adapter slot per ROW
    samples: list[bool]  # per item: does it emit a token this step
    work: "Optional[np.ndarray]"  # Pallas work schedule (TPU only)
    want_topn: bool = True


@dataclasses.dataclass
class PreparedDecode:
    """Host-built dispatch inputs for one fused K-step decode."""

    num_seqs: int
    num_steps: int
    steps_per_seq: list[int]
    token_ids: "np.ndarray"
    positions: "np.ndarray"
    limits: "np.ndarray"
    context_lens: "np.ndarray"
    block_tables: "np.ndarray"
    slots: "np.ndarray"
    tensors: SamplingTensors
    allowed_mask: "Optional[np.ndarray]"
    lora_idx: "Optional[np.ndarray]"
    # every row is plain-greedy and adapterless → the speculative path
    # may take this dispatch (engine/speculative.py)
    spec_ok: bool = False
    # any row asked for top-N logprobs: False compiles/selects the
    # sampler variant with no per-step lax.top_k and zero-width topn
    # outputs (the common serving case)
    want_topn: bool = True
    # rows whose draft cache lags (they decoded in mixed batches): each
    # entry is the padded draft-chunk inputs to catch that row up
    draft_catchups: list = dataclasses.field(default_factory=list)
    # set by SpeculativeDecoder.run when the dispatch actually speculated
    # (commit then advances each row's draft_pos)
    spec_ran: bool = False
    # chained wave (async scheduling): which step row of the PREVIOUS
    # wave's device outputs feeds each row's input token
    chain_idx: "Optional[np.ndarray]" = None


@dataclasses.dataclass
class _HostSamplerOutput:
    """Sampler results pulled to host as [K, B] numpy arrays."""

    tokens: "np.ndarray"
    logprobs: "np.ndarray"
    ranks: "np.ndarray"
    topn_ids: "np.ndarray"  # [K, B, W]
    topn_logprobs: "np.ndarray"

    @staticmethod
    def from_packed(packed_dev) -> "_HostSamplerOutput":
        """Unpack sampler.pack_output's single buffer — ONE device
        fetch for the whole result (decode waves and prefill samples
        both ride this through the tunnel)."""
        # tpulint: disable=TPL202(sanctioned sync: the ONE packed fetch per wave, called from the blocking wait_* half only)
        packed = np.asarray(packed_dev)  # [..., 3+2W]
        w = (packed.shape[-1] - 3) // 2
        return _HostSamplerOutput(
            tokens=packed[..., 0],
            ranks=packed[..., 1],
            topn_ids=packed[..., 2:2 + w],
            logprobs=np.ascontiguousarray(
                packed[..., 2 + w]).view(np.float32),
            topn_logprobs=np.ascontiguousarray(
                packed[..., 3 + w:]).view(np.float32),
        )

    def token(self, k: int, i: int) -> "SampledToken":
        return SampledToken(
            token_id=int(self.tokens[k, i]),
            logprob=float(self.logprobs[k, i]),
            rank=int(self.ranks[k, i]),
            topn_ids=self.topn_ids[k, i].tolist(),
            topn_logprobs=self.topn_logprobs[k, i].tolist(),
        )


class ModelRunner:
    def __init__(self, config: "EngineConfig", model, params, mesh=None):
        self.config = config
        self.model = model
        cache_cfg = config.cache_config
        mcfg = config.model_config
        self.block_size = cache_cfg.block_size
        self.num_slots = cache_cfg.num_blocks * cache_cfg.block_size
        self.max_blocks_per_seq = -(-mcfg.max_model_len // self.block_size)

        # distributed: shard params/caches over the mesh; the XLA SPMD
        # partitioner propagates Megatron TP through the step fns
        # (parallel/sharding.py).  tp=1 single-chip keeps the fast path.
        pcfg = config.parallel_config
        if mesh is None:
            from vllm_tgis_adapter_tpu.parallel.mesh import (
                mesh_from_parallel_config,
            )

            mesh = mesh_from_parallel_config(pcfg)
        self.mesh = mesh
        if mesh is not None:
            from vllm_tgis_adapter_tpu.parallel import (
                cache_sharding,
                data_sharding,
                shard_llama_params,
                validate_tp_divisibility,
            )

            validate_tp_divisibility(mcfg, mesh.shape["tp"])
            sp = dict(mesh.shape).get("sp", 1)
            if sp > 1:
                # fail at boot, not inside the first jitted prefill: the
                # ring requires every padded sequence length to split
                # evenly across the sp axis
                bad = [
                    b for b in config.scheduler_config.prefill_buckets
                    if b % sp
                ]
                if bad:
                    raise ValueError(
                        f"sequence_parallel_size={sp} does not divide "
                        f"prefill bucket(s) {bad}; adjust "
                        "--sequence-parallel-size or the bucket list"
                    )
            params = shard_llama_params(mesh, params)
            # allocate the cache sharded from the start: the pool is sized
            # against the mesh's AGGREGATE HBM, so materialising it on one
            # device first would OOM exactly like an unsharded weight load
            sh = cache_sharding(mesh)
            caches = jax.jit(
                lambda: model.make_kv_caches(
                    self.num_slots, cache_cfg.cache_dtype
                ),
                out_shardings=(sh, sh),
            )()
            self._data_sharding = data_sharding(mesh)
        else:
            caches = model.make_kv_caches(self.num_slots, cache_cfg.cache_dtype)
            self._data_sharding = None
        self.params = params
        self.caches = caches
        # pallas kernels must be shard_map-wrapped under a TP mesh; the
        # mesh travels on the model so each engine's retraces see its own
        # (ops/attention.py dispatch), as does the sequence-parallel
        # attention style
        model.mesh = mesh
        model.sp_mode = getattr(pcfg, "sequence_parallel_mode", "ring")
        if mesh is not None and model.sp_mode == "ulysses":
            sp = dict(mesh.shape).get("sp", 1)
            tp = mesh.shape["tp"]
            if sp > 1 and (
                (mcfg.num_heads // tp) % sp
                or (mcfg.num_kv_heads // tp) % sp
            ):
                raise ValueError(
                    f"--sequence-parallel-mode ulysses needs sp={sp} to "
                    f"divide the per-tp-shard head counts "
                    f"(heads={mcfg.num_heads // tp}, "
                    f"kv_heads={mcfg.num_kv_heads // tp} at tp={tp}); "
                    "use ring mode or adjust sp/tp"
                )

        # ragged unified data path (--attention-backend=ragged): the
        # decode programs below trace the ragged kernel instead of the
        # bucketed variant ladder, and _ragged_fn serves mixed steps
        self._ragged_backend = (
            getattr(config, "attention_backend", "bucketed") == "ragged"
        )
        # buffer donation lets XLA update the KV cache in place; host
        # platforms don't implement donation and warn, so gate it
        donate = (1,) if jax.default_backend() == "tpu" else ()
        # recompile tracking (compile_tracker.py): every jitted entry
        # point is wrapped so a compile-cache miss records the (bucket,
        # batch, steps) shape that triggered it — on TPU a leak past the
        # scheduler's buckets costs a 20-40s serving stall per shape
        self._prefill_fn = track_jit(
            "prefill",
            jax.jit(model.prefill, donate_argnums=donate),
            # solo and packed prefill retrace separately (seg_starts
            # changes the call arity) — label them apart so the
            # compile-lattice evidence counts both programs
            label=lambda args, kwargs: f"tokens={args[2].shape[0]}" + (
                ",packed" if kwargs.get("seg_starts") is not None else ""
            ),
        )
        self._decode_fn = self._build_decode_fn()

        max_seqs = config.scheduler_config.max_num_seqs
        self.seen = self._put(jnp.zeros((max_seqs, mcfg.vocab_size), bool))
        self._rng = np.random.default_rng(config.seed)
        self.lora_stacks = None
        self._lora_version = 0  # manager starts at 0 = nothing loaded
        # paged adapter pool (engine/adapter_pool.py): device residency
        # and async host→device streaming replace the sync_lora
        # full-stack rebuild.  Stacks exist (zeroed) from boot, so the
        # serving programs compile WITH lora args once and adapter
        # swaps never add a compile shape.
        self.adapter_pool = None
        lcfg = config.lora_config
        if lcfg.enabled and lcfg.pool:
            from vllm_tgis_adapter_tpu.engine.adapter_pool import (
                AdapterPool,
            )

            self.adapter_pool = AdapterPool(
                mcfg,
                lcfg.max_loras,
                lcfg.max_lora_rank,
                self._put,
                prefetch_concurrency=lcfg.prefetch_concurrency,
            )
            self.lora_stacks = self.adapter_pool.stacks
            self.adapter_pool.on_commit = (
                lambda stacks: setattr(self, "lora_stacks", stacks)
            )

        # chunked prefill: non-first chunks attend to prior context through
        # the paged cache (models/llama.py prefill_chunk)
        self._prefill_chunk_fn = track_jit(
            "prefill_chunk",
            jax.jit(
                functools.partial(
                    model.prefill_chunk, block_size=self.block_size
                ),
                donate_argnums=donate,
            ),
            label=lambda args, kwargs: f"tokens={args[2].shape[0]}",
        )
        self._seen_pad_lens = sorted(
            set(config.scheduler_config.prefill_buckets)
        )
        # unified ragged step: one program per flat-length bucket serves
        # every mixed prefill+decode batch (ops/ragged_attention.py) —
        # the compile lattice the bucketed path spreads over
        # solo/packed/chunk prefill entry points collapses here
        self._ragged_fn = None
        # per-flat-bucket high-water mark for the Pallas work-schedule
        # width (a compile shape of the ragged step; see prepare_ragged)
        self._ragged_work_hwm: dict[int, int] = {}
        if self._ragged_backend:
            self._ragged_fn = track_jit(
                "ragged_step",
                jax.jit(
                    functools.partial(
                        model.ragged_forward, block_size=self.block_size
                    ),
                    donate_argnums=donate,
                ),
                label=lambda args, kwargs: f"tokens={args[2].shape[0]}"
                + (
                    f",work={kwargs['work'].shape[1]}"
                    if kwargs.get("work") is not None
                    else ""
                ),
            )
        # draft-model speculative decoding; attached by the engine when
        # --speculative-model is configured (engine/speculative.py)
        self.spec = None
        # --swap-space: donated jitted scatter, built on first swap-in
        self._restore_kv_fn = None
        # host KV tier (engine/kv_tier.py): fixed-block-shape gather /
        # scatter programs, built on first demotion / promotion — ONE
        # compile shape each (slots is always block_size), so the tier
        # adds zero shapes to the serving lattice past its first use
        self._gather_kv_fn = None
        self._block_scatter_fn = None

    def attach_speculative(self, draft_model, draft_params) -> None:  # noqa: ANN001
        from vllm_tgis_adapter_tpu.engine.speculative import (
            SpeculativeDecoder,
        )

        self.spec = SpeculativeDecoder(
            self, draft_model, draft_params,
            self.config.speculative.num_speculative_tokens,
        )

    def sync_lora(self, manager) -> None:
        """Legacy slow path: rebuild the stacked adapter tensors when
        the registry changed (hot load/evict).  One compiled program
        serves every adapter — slots and padded ranks keep shapes
        constant across reloads.

        With the paged pool (--lora-pool, the default) this is a no-op:
        the pool streams per-slot updates asynchronously instead.  On
        the legacy path the rebuild runs from the registry's off-loop
        resync hook at LOAD time (lora.LoRAManager.load_lora_adapter),
        so the plan_step call sees a matching version and this is free
        in the step path; it remains as the correctness backstop for
        offline engines driving plan_step directly."""
        if getattr(self, "adapter_pool", None) is not None:
            return
        if manager is None or manager.version == self._lora_version:
            return
        from vllm_tgis_adapter_tpu.engine.lora import build_lora_stacks

        lcfg = self.config.lora_config
        stacks = build_lora_stacks(
            self.config.model_config, manager.max_loras,
            lcfg.max_lora_rank, manager,
        )
        # subclasses override placement (the pipeline runner slices per
        # stage); the host-side build above stays shared so the version
        # protocol cannot drift between runners
        self.lora_stacks = self._place_lora_stacks(stacks)
        self._lora_version = manager.version

    def _place_lora_stacks(self, stacks):  # noqa: ANN001
        return jax.tree.map(self._put, stacks)

    def _build_decode_fn(self):
        """Fused K-step decode+sample program (SURVEY.md §7 recompilation
        discipline: one compiled program per batch-width bucket).

        A ``lax.scan`` over the step axis runs the whole
        decode → penalties → sample → feed-back loop on device, so the
        host pays one dispatch and one [K, B] result transfer for K
        tokens per sequence instead of K round-trips.  Per-step KV slots
        are computed on device from the block tables; rows finish early
        via the ``limits`` mask (their writes are dropped and their
        sampled tokens discarded by the host).

        Transfer packing: the eleven per-row int32 inputs travel as ONE
        ``[11, B]`` array and the five float32 sampling knobs as one
        ``[5, B]`` array; results come back as one int and one float
        array.  Each host↔device buffer is its own transfer at the
        runtime layer — and through a tunnel-attached chip, its own
        network round trip — so per-dispatch overhead scales with the
        BUFFER count, not the byte count (these are all tiny).
        """
        model = self.model
        block_size = self.block_size
        # ragged backend: the fused wave runs the SAME unified kernel
        # as mixed steps (each row a one-token span) — the decode
        # variant ladder (folded → perhead → xla) is retired on this
        # path, and the compile labels split by backend so the
        # compile-count-by-backend metric attributes shapes correctly
        use_ragged = self._ragged_backend

        def decode_steps(
            params,
            caches,
            seen,  # [max_seqs, V] full seen-token matrix (carried)
            ints,  # [11, B] i32: tokens, positions0, limits, ctx_lens0,
            #      row_slots, top_k, len_penalty_start, min_tokens,
            #      eos_token_id, gen_len, base_key (uint32 bitcast)
            floats,  # [5, B] f32: temperature, top_p, typical_p,
            #        repetition_penalty, len_penalty_decay
            block_tables,  # [B, max_blocks]
            allowed_mask,  # [B, V] bool or None (FSM-constrained rows)
            lora,  # LoRAStacks or None
            lora_idx,  # [B] adapter slot per row or None
            num_steps: int,  # static: steps fused into this dispatch
            want_topn: bool = True,  # static: any row wants top-N logprobs
        ):
            tokens0 = ints[0]
            positions0 = ints[1]
            limits = ints[2]
            context_lens0 = ints[3]
            row_slots = ints[4]
            tensors = SamplingTensors(
                temperature=floats[0],
                top_k=ints[5],
                top_p=floats[1],
                typical_p=floats[2],
                repetition_penalty=floats[3],
                len_penalty_start=ints[6],
                len_penalty_decay=floats[4],
                min_tokens=ints[7],
                eos_token_id=ints[8],
                gen_len=ints[9],
                base_key=jax.lax.bitcast_convert_type(
                    ints[10], jnp.uint32
                ),
            )
            rows = jnp.clip(row_slots, 0, None)
            max_blocks = block_tables.shape[1]

            def step(carry, k):
                caches, seen, tokens = carry
                pos = positions0 + k
                active = (pos <= limits) & (row_slots >= 0)
                blk = jnp.take_along_axis(
                    block_tables,
                    jnp.clip(pos // block_size, 0, max_blocks - 1)[:, None],
                    axis=1,
                )[:, 0]
                slot = jnp.where(
                    active, blk * block_size + pos % block_size, -1
                )
                logits, caches = model.decode(
                    params, caches, tokens, pos, slot, block_tables,
                    context_lens0 + k, block_size, lora, lora_idx,
                    use_ragged_kernel=use_ragged,
                )
                t_k = dataclasses.replace(
                    tensors, gen_len=tensors.gen_len + k
                )
                seen_rows = jnp.take(seen, rows, axis=0)
                out = sampler_mod.sample(
                    logits, seen_rows, t_k, allowed_mask=allowed_mask,
                    want_topn=want_topn,
                )
                seen = sampler_mod.update_seen(
                    seen, jnp.where(active, row_slots, -1), out.tokens
                )
                return (caches, seen, out.tokens), out

            (caches, seen, _), outs = jax.lax.scan(
                step, (caches, seen, tokens0), jnp.arange(num_steps)
            )
            # ONE packed result buffer per wave (sampler.pack_output):
            # the whole wave's results come back in a single fetch
            return caches, seen, sampler_mod.pack_output(outs)

        donate = (1, 2) if jax.default_backend() == "tpu" else ()

        def chained_decode_steps(
            params, caches, seen,
            prev_ints_out,  # [K_prev, B, 3+2W] the in-flight wave's packed
            #     outputs (column 0 = sampled tokens; see packed_out)
            chain_idx,  # [B] i32: last live step per row in prev wave
            ints, floats, block_tables, allowed_mask, lora, lora_idx,
            num_steps: int,
            want_topn: bool = True,
        ):
            # chained wave (async scheduling): the input token of each row
            # is the PREVIOUS wave's final sampled token, read directly
            # from its device-resident outputs — no host round trip
            # between decode waves (packed layout: column 0 is tokens)
            tokens0 = jnp.take_along_axis(
                prev_ints_out[..., 0], chain_idx[None, :], axis=0
            )[0]
            ints = ints.at[0].set(tokens0)
            return decode_steps(
                params, caches, seen, ints, floats, block_tables,
                allowed_mask, lora, lora_idx, num_steps, want_topn,
            )

        prefix = "ragged_" if use_ragged else ""
        self._chained_decode_fn = track_jit(
            f"{prefix}chained_decode",
            jax.jit(chained_decode_steps, static_argnums=(11, 12),
                    donate_argnums=donate),
            # ints is arg 5 ([11, B]), num_steps is static arg 11
            label=lambda args, kwargs:
                f"batch={args[5].shape[1]},steps={args[11]}",
        )
        return track_jit(
            f"{prefix}decode",
            jax.jit(decode_steps, static_argnums=(9, 10),
                    donate_argnums=donate),
            # ints is arg 3 ([11, B]), num_steps is static arg 9
            label=lambda args, kwargs:
                f"batch={args[3].shape[1]},steps={args[9]}",
        )

    def _put(self, x) -> jax.Array:
        """Host array → device; replicated over the mesh when distributed
        so every tp shard sees the full batch (parallel/sharding.py)."""
        if self._data_sharding is not None:
            return jax.device_put(x, self._data_sharding)
        return jnp.asarray(x)

    def new_fallback_seed(self) -> int:
        """Engine-drawn PRNG material for requests without an explicit seed."""
        return int(self._rng.integers(0, 2**32, dtype=np.uint32))

    # ------------------------------------------------------------- KV swap

    def extract_kv(self, slots: list[int]) -> tuple:
        """Gather ``slots`` of both caches to host (--swap-space swap-out;
        the transfer is one device gather + copy per cache)."""
        k_cache, v_cache = self.caches
        idx = jnp.asarray(slots, jnp.int32)
        return (
            np.asarray(jnp.take(k_cache, idx, axis=2)),  # tpulint: disable=TPL202(swap-out IS the device→host copy; runs on a clean dispatch boundary)
            np.asarray(jnp.take(v_cache, idx, axis=2)),  # tpulint: disable=TPL202(swap-out IS the device→host copy; runs on a clean dispatch boundary)
        )

    @staticmethod
    def _scatter_kv(k_cache, v_cache, idx, k_new, v_new):  # noqa: ANN001, ANN205
        # positive out-of-range pad indices are dropped by mode="drop"
        return (
            k_cache.at[:, :, idx, :].set(
                k_new.astype(k_cache.dtype), mode="drop"
            ),
            v_cache.at[:, :, idx, :].set(
                v_new.astype(v_cache.dtype), mode="drop"
            ),
        )

    def reseed_seen_row(self, slot: int, token_ids: list[int]) -> None:
        """Reset one batch row of the seen-token matrix (swap-in: the
        freshly assigned slot may hold a previous occupant's stale row,
        and the prefill seeding that normally resets it is skipped)."""
        pad = self._seen_pad_len(len(token_ids))
        arr = np.full(pad, -1, np.int32)
        arr[: len(token_ids)] = token_ids
        self.seen = sampler_mod.set_seen_row(
            self.seen, self._put(np.asarray(slot)), self._put(arr)
        )

    def restore_kv(self, slots: list[int], k_host, v_host) -> None:
        """Scatter a host KV copy into ``slots`` (swap-in).  Must only run
        on a clean dispatch boundary: the functional update rebinds
        self.caches, so an in-flight dispatch's commit would drop it.

        Donated jit: the KV pool is sized to ~90% of free HBM, so an
        eager (non-donating) scatter would transiently hold TWO full
        caches and OOM exactly when swap triggers (memory pressure).
        Slot counts bucket to powers of two (pads scatter out of range
        and drop) so compile variety stays logarithmic."""
        if self._restore_kv_fn is None:
            donate = (0, 1) if jax.default_backend() == "tpu" else ()
            self._restore_kv_fn = track_jit(
                "restore_kv",
                jax.jit(self._scatter_kv, donate_argnums=donate),
                label=lambda args, kwargs: f"slots={args[2].shape[0]}",
            )
        n = len(slots)
        bucket = 1
        while bucket < n:
            bucket *= 2
        pad = [(0, 0), (0, 0), (0, bucket - n), (0, 0)]
        idx = np.full(bucket, self.num_slots, np.int32)  # OOB → dropped
        idx[:n] = slots
        k_cache, v_cache = self.caches
        self.caches = self._restore_kv_fn(
            k_cache, v_cache, jnp.asarray(idx),
            self._put(np.pad(np.asarray(k_host), pad)),
            self._put(np.pad(np.asarray(v_host), pad)),
        )

    # ------------------------------------------------------- host KV tier

    @staticmethod
    def _gather_kv(k_cache, v_cache, idx):  # noqa: ANN001, ANN205
        return (
            jnp.take(k_cache, idx, axis=2),
            jnp.take(v_cache, idx, axis=2),
        )

    def gather_kv_block(self, slots: list[int]) -> tuple:
        """Enqueue a device-side gather of ONE page's slots for host-tier
        demotion (engine/kv_tier.py).  Returns DEVICE arrays without
        blocking — the tier's worker thread does the device→host copy —
        and the gather is ordered before any later dispatch that could
        overwrite the page, so the content read is the content current
        at enqueue even if the page is reclaimed immediately after.
        ``slots`` is always exactly block_size long: one compiled shape,
        forever."""
        if self._gather_kv_fn is None:
            self._gather_kv_fn = track_jit(
                "gather_kv",
                jax.jit(self._gather_kv),
                label=lambda args, kwargs: f"slots={args[2].shape[0]}",
            )
        k_cache, v_cache = self.caches
        return self._gather_kv_fn(
            k_cache, v_cache, jnp.asarray(slots, jnp.int32)
        )

    def restore_kv_block(self, slots: list[int], k_dev, v_dev) -> None:
        """Scatter one promoted page into its freshly allocated slots
        (host-tier promotion apply).  Same clean-dispatch-boundary
        contract as ``restore_kv`` (the functional update rebinds
        ``self.caches``); the inputs are already device-resident (the
        tier's assembly thread staged them), so the loop-side cost is
        one jitted dispatch.  Fixed [block_size] index shape: one
        compiled program covers every promotion."""
        if self._block_scatter_fn is None:
            donate = (0, 1) if jax.default_backend() == "tpu" else ()
            self._block_scatter_fn = track_jit(
                "scatter_kv",
                jax.jit(self._scatter_kv, donate_argnums=donate),
                label=lambda args, kwargs: f"slots={args[2].shape[0]}",
            )
        k_cache, v_cache = self.caches
        self.caches = self._block_scatter_fn(
            k_cache, v_cache, jnp.asarray(slots, jnp.int32), k_dev, v_dev
        )

    # --------------------------------------------------------------- prefill

    def _seen_pad_len(self, n: int) -> int:
        """Pad length for seen-matrix seeding (bounded compile shapes)."""
        for b in self._seen_pad_lens:
            if n <= b:
                return b
        quantum = self._seen_pad_lens[-1]
        return -(-n // quantum) * quantum

    def prepare_prefill(self, plan: "PrefillPlan") -> "PreparedPrefill":
        """Host half: snapshot everything the dispatch needs from the
        sequence, so the engine lock can be released during the (slow)
        device execution — an abort mid-dispatch then cannot race the
        input build."""
        seq = plan.seq
        t = len(plan.token_ids)
        bucket = plan.bucket_len

        token_ids = np.zeros(bucket, np.int32)
        token_ids[:t] = plan.token_ids
        positions = plan.start_pos + np.arange(bucket, dtype=np.int32)
        slot_mapping = np.full(bucket, -1, np.int32)
        slot_mapping[:t] = plan.slots

        # chunked prompt-logprobs: EVERY chunk of an lp request computes
        # full-bucket logits and its per-row targets; the table
        # accumulates at commit (core._append_prompt_logprobs).  A
        # preemption-resume whose table is already complete skips the
        # extra logits work entirely.
        n_prompt = seq.num_prompt_tokens
        table_done = (
            seq.prompt_logprobs is not None
            and len(seq.prompt_logprobs) >= n_prompt
        )
        want_prompt_lp = (
            seq.params.prompt_logprobs is not None and not table_done
        )
        lp_targets = None
        lp_rows = 0
        if want_prompt_lp:
            # row i predicts global position start+i+1; rows past the
            # last PROMPT position carry no entry (resume re-runs cover
            # generated positions too)
            lp_rows = max(0, min(t, n_prompt - 1 - plan.start_pos))
            all_ids = seq.all_token_ids
            lp_targets = np.full(bucket, -1, np.int32)
            lp_targets[:lp_rows] = all_ids[
                plan.start_pos + 1 : plan.start_pos + 1 + lp_rows
            ]
            want_prompt_lp = lp_rows > 0
        # logits rows: the sampled row only, except prompt-logprob requests
        # which need every bucket row.  (The bucket is already the smallest
        # compile shape ≥ t, so an exact [t]-row gather would only change
        # shapes per-request and trade bounded padding for recompiles.)
        logits_indices = (
            np.arange(bucket, dtype=np.int32)
            if want_prompt_lp
            else np.asarray([t - 1], np.int32)
        )

        block_table = None
        if plan.start_pos > 0:
            block_table = np.zeros(self.max_blocks_per_seq, np.int32)
            blocks = seq.blocks.blocks
            block_table[: len(blocks)] = blocks

        seen_tokens = None
        tensors = None
        allowed_row = None
        if plan.is_final:
            all_ids = seq.all_token_ids
            padded = self._seen_pad_len(len(all_ids))
            seen_tokens = np.full(padded, -1, np.int32)
            seen_tokens[: len(all_ids)] = all_ids
            seeds = np.asarray([seq.fallback_seed], np.uint32)
            tensors = SamplingTensors.from_params(
                [seq.params],
                eos_token_id=self.config.model_config.eos_token_id,
                gen_lens=[seq.num_output_tokens],
                fallback_seeds=seeds,
            )
            if seq.fsm is not None:
                vocab = self.config.model_config.vocab_size
                allowed_row = np.zeros(vocab, bool)
                fsm_row = seq.fsm.allowed_row(seq.fsm_state)
                allowed_row[: len(fsm_row)] = fsm_row

        return PreparedPrefill(
            t=t,
            token_ids=token_ids,
            positions=positions,
            slot_mapping=slot_mapping,
            start_pos=plan.start_pos,
            is_final=plan.is_final,
            block_table=block_table,
            logits_indices=logits_indices,
            want_prompt_lp=want_prompt_lp,
            lp_targets=lp_targets,
            lp_rows=lp_rows,
            row_slot=seq.slot,
            seen_tokens=seen_tokens,
            tensors=tensors,
            allowed_row=allowed_row,
            lora_slot=seq.lora_slot,
            spec_eligible=seq.spec_eligible,
        )

    def dispatch_prefill(self, prep: "PreparedPrefill"):
        """Enqueue the prefill's device work WITHOUT blocking on results.

        JAX dispatch is asynchronous: every call below returns device
        arrays (futures) immediately; the blocking host transfers live in
        ``wait_prefill``.  The async engine exploits the split to keep
        the device fed — while one dispatch executes, the next step is
        planned and enqueued (engine/async_llm.py step loop).
        """
        failpoints.fire("runner.dispatch_prefill")
        t = prep.t
        lora_args = ()
        if self.lora_stacks is not None:
            lora_args = (
                self.lora_stacks,
                self._put(np.asarray(prep.lora_slot, np.int32)),
            )
        common = (
            self.params,
            self.caches,
            self._put(prep.token_ids),
            self._put(prep.positions),
            self._put(prep.slot_mapping),
            self._put(np.asarray(t, np.int32)),
        )
        if prep.start_pos == 0:
            # whole prompt (or the first chunk): flash causal attention is
            # exact — there is no earlier context to see
            logits, self.caches = self._prefill_fn(
                *common, self._put(prep.logits_indices), *lora_args
            )
        else:
            logits, self.caches = self._prefill_chunk_fn(
                *common,
                self._put(prep.block_table),
                self._put(prep.logits_indices),
                *lora_args,
            )
        if self.spec is not None and prep.spec_eligible:
            # the draft model needs the prompt in ITS cache before it can
            # propose continuations
            self.spec.draft_prefill(prep)
        lp_parts = None
        if prep.want_prompt_lp:
            lp_parts = sampler_mod.pack_prompt_logprob_parts(
                sampler_mod.prompt_logprob_info(
                    logits, self._put(prep.lp_targets)
                )
            )
        if not prep.is_final:
            # mid-prompt chunk: nothing to sample, but an lp chunk's
            # per-row table travels back for accumulation
            if lp_parts is None:
                return None
            return {"out": None, "lp": lp_parts}

        if prep.want_prompt_lp:
            last_logits = logits[t - 1][None]
        else:
            last_logits = logits

        # seed this row's seen-token matrix with the full prompt, sample
        self.seen = sampler_mod.set_seen_row(
            self.seen,
            self._put(np.asarray(prep.row_slot)),
            self._put(prep.seen_tokens),
        )
        allowed_mask = (
            self._put(prep.allowed_row[None, :])
            if prep.allowed_row is not None
            else None
        )
        seen_rows = jnp.take(
            self.seen,
            jnp.clip(jnp.asarray([prep.row_slot]), 0, None),
            axis=0,
        )
        out = sampler_mod.sample(
            last_logits,
            seen_rows,
            jax.tree.map(self._put, prep.tensors),
            allowed_mask=allowed_mask,
        )
        self.seen = sampler_mod.update_seen(
            self.seen, jnp.asarray([prep.row_slot]), out.tokens
        )
        return {"out": sampler_mod.pack_output(out), "lp": lp_parts}

    def wait_prefill(
        self, prep: "PreparedPrefill", handle
    ) -> tuple[Optional[SampledToken], Optional[PromptLogprobInfo]]:
        """Blocking half: pull the dispatched results to host (one
        fetch per packed buffer)."""
        if handle is None:
            return None, None  # mid-prompt chunk without lp accumulation
        prompt_info = None
        if handle["lp"] is not None:
            prompt_info = PromptLogprobInfo.from_packed(
                handle["lp"], prep.lp_rows
            )
        if handle["out"] is None:
            return None, prompt_info  # lp chunk: table rows only
        host = _HostSamplerOutput.from_packed(handle["out"][None])
        return host.token(0, 0), prompt_info

    def execute_prefill(
        self, prep: "PreparedPrefill"
    ) -> tuple[Optional[SampledToken], Optional[PromptLogprobInfo]]:
        """Device half; touches only runner-owned state."""
        return self.wait_prefill(prep, self.dispatch_prefill(prep))

    def run_prefill(
        self, plan: "PrefillPlan"
    ) -> tuple[Optional[SampledToken], Optional[PromptLogprobInfo]]:
        return self.execute_prefill(self.prepare_prefill(plan))

    # -------------------------------------------------------- packed prefill

    def prepare_packed_prefill(self, plan) -> "PreparedPackedPrefill":
        """Host half for a multi-prompt packed prefill
        (scheduler.PackedPrefillPlan): concatenate the prompts on the
        token axis, record per-segment starts / sampling rows."""
        from vllm_tgis_adapter_tpu.engine.scheduler import MAX_PACK

        items = plan.items
        bucket = plan.bucket_len
        k = len(items)
        token_ids = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        slot_mapping = np.full(bucket, -1, np.int32)
        seg_starts = np.full(MAX_PACK, bucket, np.int32)
        logits_indices = np.zeros(MAX_PACK, np.int32)
        row_slots = np.full(MAX_PACK, -1, np.int32)
        seeds = np.zeros(MAX_PACK, np.uint32)
        # one shared pad width (the largest item's seen bucket) so the
        # whole pack seeds the seen matrix in ONE batched dispatch
        pad = max(
            self._seen_pad_len(len(it.seq.all_token_ids)) for it in items
        )
        seen_tokens = np.full((MAX_PACK, pad), -1, np.int32)
        off = 0
        for i, it in enumerate(items):
            t = len(it.token_ids)
            token_ids[off : off + t] = it.token_ids
            positions[off : off + t] = np.arange(t, dtype=np.int32)
            slot_mapping[off : off + t] = it.slots
            seg_starts[i] = off
            logits_indices[i] = off + t - 1
            row_slots[i] = it.seq.slot
            seeds[i] = it.seq.fallback_seed
            all_ids = it.seq.all_token_ids
            seen_tokens[i, : len(all_ids)] = all_ids
            off += t

        params_list = [it.seq.params for it in items] + [None] * (
            MAX_PACK - k
        )
        gen_lens = [it.seq.num_output_tokens for it in items] + [0] * (
            MAX_PACK - k
        )
        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )

        allowed_mask = None
        if any(it.seq.fsm is not None for it in items):
            vocab = self.config.model_config.vocab_size
            allowed_mask = np.ones((MAX_PACK, vocab), bool)
            for i, it in enumerate(items):
                if it.seq.fsm is not None:
                    row = it.seq.fsm.allowed_row(it.seq.fsm_state)
                    allowed_mask[i, : len(row)] = row
                    allowed_mask[i, len(row):] = False

        return PreparedPackedPrefill(
            bucket=bucket,
            num_items=k,
            total_tokens=off,
            token_ids=token_ids,
            positions=positions,
            slot_mapping=slot_mapping,
            seg_starts=seg_starts,
            logits_indices=logits_indices,
            row_slots=row_slots,
            seen_tokens=seen_tokens,
            tensors=tensors,
            allowed_mask=allowed_mask,
            lora_slot=items[0].seq.lora_slot,
        )

    def _sample_rows(
        self,
        logits,
        row_slots: np.ndarray,
        seed_slots: np.ndarray,
        seed_tokens: np.ndarray,
        tensors: "SamplingTensors",
        allowed_mask,
        want_topn: bool = True,
    ):
        """Post-forward sampler tail shared by the batched multi-row
        dispatchers (packed prefill, ragged): seed the seen matrix for
        finishing prompts (``seed_slots`` < 0 drop in the scatter; a
        batch with nothing to seed skips the dispatch entirely), gather
        per-row seen state, sample, record the sampled tokens."""
        if (seed_slots >= 0).any():
            self.seen = sampler_mod.set_seen_rows(
                self.seen,
                self._put(seed_slots),
                self._put(seed_tokens),
            )
        seen_rows = jnp.take(
            self.seen,
            jnp.clip(self._put(row_slots), 0, None),
            axis=0,
        )
        out = sampler_mod.sample(
            logits,
            seen_rows,
            jax.tree.map(self._put, tensors),
            allowed_mask=(
                self._put(allowed_mask)
                if allowed_mask is not None
                else None
            ),
            want_topn=want_topn,
        )
        self.seen = sampler_mod.update_seen(
            self.seen, self._put(row_slots), out.tokens
        )
        return sampler_mod.pack_output(out)

    def dispatch_packed_prefill(self, prep: "PreparedPackedPrefill"):
        """Enqueue ONE forward over the packed bucket (block-diagonal
        causal mask via seg_starts) plus the batched sampler over the
        MAX_PACK last-token rows; no blocking transfers (see
        dispatch_prefill)."""
        lora_args = ()
        if self.lora_stacks is not None:
            lora_args = (
                self.lora_stacks,
                self._put(np.asarray(prep.lora_slot, np.int32)),
            )
        logits, self.caches = self._prefill_fn(
            self.params,
            self.caches,
            self._put(prep.token_ids),
            self._put(prep.positions),
            self._put(prep.slot_mapping),
            self._put(np.asarray(prep.total_tokens, np.int32)),
            self._put(prep.logits_indices),
            *lora_args,
            seg_starts=self._put(prep.seg_starts),
        )
        return self._sample_rows(
            logits,
            prep.row_slots,
            prep.row_slots,
            prep.seen_tokens,
            prep.tensors,
            prep.allowed_mask,
        )

    def wait_packed_prefill(
        self, prep: "PreparedPackedPrefill", handle
    ) -> list[SampledToken]:
        """Blocking half: one SampledToken per real packed prompt, in
        pack order (one device fetch for the whole pack)."""
        host = _HostSamplerOutput.from_packed(handle[None])
        return [host.token(0, i) for i in range(prep.num_items)]

    def execute_packed_prefill(
        self, prep: "PreparedPackedPrefill"
    ) -> list[SampledToken]:
        return self.wait_packed_prefill(
            prep, self.dispatch_packed_prefill(prep)
        )

    # ---------------------------------------------------------------- ragged

    def prepare_ragged(self, plan) -> "PreparedRagged":
        """Host half of one unified ragged step (scheduler.RaggedPlan):
        concatenate every item's span on the flat token axis, build the
        per-sequence descriptors, and snapshot the sampling inputs for
        the rows that emit a token (decode rows + final chunks)."""
        items = plan.items
        bucket = plan.token_bucket
        s_max = self.config.scheduler_config.max_num_seqs

        token_ids = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        slot_mapping = np.full(bucket, -1, np.int32)
        seq_starts = np.full(s_max + 1, bucket, np.int32)
        pos_base = np.zeros(s_max, np.int32)
        block_tables = np.zeros((s_max, self.max_blocks_per_seq), np.int32)
        logits_indices = np.zeros(s_max, np.int32)
        row_slots = np.full(s_max, -1, np.int32)
        seed_slots = np.full(s_max, -1, np.int32)
        seeds = np.zeros(s_max, np.uint32)
        lora_idx = None
        if self.lora_stacks is not None:
            lora_idx = np.zeros(bucket, np.int32)
        # only finishing prompts seed the seen matrix (decode rows keep
        # their already-seeded row), so the pad width must not track
        # decode rows' ever-growing all_token_ids — that would retrace
        # jitted set_seen_rows at every quantum the longest running
        # generation crosses
        pad = max(
            (
                self._seen_pad_len(len(it.seq.all_token_ids))
                for it in items
                if it.is_final and not it.is_decode
            ),
            default=self._seen_pad_lens[0],
        )
        seed_tokens = np.full((s_max, pad), -1, np.int32)
        spans: list[tuple[int, int, int]] = []
        samples: list[bool] = []
        off = 0
        for i, it in enumerate(items):
            t = len(it.token_ids)
            token_ids[off : off + t] = it.token_ids
            positions[off : off + t] = it.start_pos + np.arange(
                t, dtype=np.int32
            )
            slot_mapping[off : off + t] = it.slots
            seq_starts[i] = off
            pos_base[i] = it.start_pos
            blocks = it.seq.blocks.blocks
            block_tables[i, : len(blocks)] = blocks
            if lora_idx is not None:
                lora_idx[off : off + t] = it.seq.lora_slot
            spans.append((off, t, it.start_pos))
            samples.append(it.is_final)
            if it.is_final:
                logits_indices[i] = off + t - 1
                row_slots[i] = it.seq.slot
                seeds[i] = it.seq.fallback_seed
                if not it.is_decode:
                    # a prompt finishing this step seeds its seen row;
                    # decode rows keep their already-seeded row
                    all_ids = it.seq.all_token_ids
                    seed_slots[i] = it.seq.slot
                    seed_tokens[i, : len(all_ids)] = all_ids
            off += t
        seq_starts[len(items)] = off

        params_list = [
            it.seq.params if it.is_final else None for it in items
        ] + [None] * (s_max - len(items))
        gen_lens = [
            it.seq.num_output_tokens if it.is_final else 0 for it in items
        ] + [0] * (s_max - len(items))
        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )

        allowed_mask = None
        if any(
            it.seq.fsm is not None and it.is_final for it in items
        ):
            vocab = self.config.model_config.vocab_size
            allowed_mask = np.ones((s_max, vocab), bool)
            for i, it in enumerate(items):
                if it.seq.fsm is not None and it.is_final:
                    row = it.seq.fsm.allowed_row(it.seq.fsm_state)
                    allowed_mask[i, : len(row)] = row
                    allowed_mask[i, len(row):] = False

        work = None
        from vllm_tgis_adapter_tpu.ops import attention as attn_ops

        if attn_ops._use_pallas():
            from vllm_tgis_adapter_tpu.ops.ragged_attention import (
                build_work_schedule,
            )

            # same clamp + cdiv padding the kernel applies, so the
            # schedule covers exactly the kernel's query-block grid
            block_q = min(128, bucket)
            work = build_work_schedule(
                spans, block_tables,
                block_size=self.block_size, block_q=block_q,
                t_pad=-(-bucket // block_q) * block_q,
            )
            # the schedule width is a compile shape on the jitted
            # ragged step: quantize it to a per-bucket high-water mark
            # (pow2, floored) so width growth retraces log-many times
            # and steady state keeps one program per flat bucket
            width = max(
                work.shape[1],
                self._ragged_work_hwm.get(bucket, 0),
                _RAGGED_WORK_FLOOR,
            )
            self._ragged_work_hwm[bucket] = width
            if width > work.shape[1]:
                tail = np.zeros(
                    (work.shape[0], width - work.shape[1]), np.int32
                )
                # pads hold the final real block index (flags all zero
                # = no-ops), same contract as build_work_schedule's own
                tail[0, :] = work[0, -1]
                work = np.concatenate([work, tail], axis=1)

        return PreparedRagged(
            bucket=bucket,
            total_tokens=off,
            num_items=len(items),
            token_ids=token_ids,
            positions=positions,
            slot_mapping=slot_mapping,
            seq_starts=seq_starts,
            pos_base=pos_base,
            block_tables=block_tables,
            logits_indices=logits_indices,
            row_slots=row_slots,
            seed_slots=seed_slots,
            seed_tokens=seed_tokens,
            tensors=tensors,
            allowed_mask=allowed_mask,
            lora_idx=lora_idx,
            samples=samples,
            work=work,
            want_topn=any(
                it.is_final and it.seq.params.logprobs not in (None, 0)
                for it in items
            ),
        )

    def dispatch_ragged(self, prep: "PreparedRagged"):
        """Enqueue ONE forward over the mixed ragged stream plus the
        batched sampler over every emitting row; no blocking transfers
        (see dispatch_prefill)."""
        failpoints.fire("runner.dispatch_ragged")
        lora_args = ()
        if self.lora_stacks is not None:
            lora_args = (self.lora_stacks, self._put(prep.lora_idx))
        logits, self.caches = self._ragged_fn(
            self.params,
            self.caches,
            self._put(prep.token_ids),
            self._put(prep.positions),
            self._put(prep.slot_mapping),
            self._put(prep.seq_starts),
            self._put(prep.pos_base),
            self._put(np.asarray(prep.total_tokens, np.int32)),
            self._put(prep.block_tables),
            self._put(prep.logits_indices),
            *lora_args,
            work=self._put(prep.work) if prep.work is not None else None,
        )
        return self._sample_rows(
            logits,
            prep.row_slots,
            prep.seed_slots,
            prep.seed_tokens,
            prep.tensors,
            prep.allowed_mask,
            want_topn=prep.want_topn,
        )

    def wait_ragged(
        self, prep: "PreparedRagged", handle
    ) -> list[Optional[SampledToken]]:
        """Blocking half: one entry per plan item, in stream order —
        a SampledToken for emitting items (decode rows, final chunks),
        None for mid-prompt chunks (one device fetch for the batch)."""
        host = _HostSamplerOutput.from_packed(handle[None])
        return [
            host.token(0, i) if prep.samples[i] else None
            for i in range(prep.num_items)
        ]

    def execute_ragged(
        self, prep: "PreparedRagged"
    ) -> list[Optional[SampledToken]]:
        return self.wait_ragged(prep, self.dispatch_ragged(prep))

    # ---------------------------------------------------------------- decode

    def prepare_decode(self, plan: "DecodePlan") -> "PreparedDecode":
        """Host half of a fused K-step decode dispatch (see
        prepare_prefill for the locking rationale)."""
        seqs = plan.seqs
        b = plan.batch_bucket

        token_ids = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        limits = np.full(b, -1, np.int32)
        context_lens = np.ones(b, np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        slots = np.full(b, -1, np.int32)
        seeds = np.zeros(b, np.uint32)
        for i, seq in enumerate(seqs):
            pos = seq.num_tokens - 1  # the last sampled token runs first
            token_ids[i] = seq.all_token_ids[-1]
            positions[i] = pos
            limits[i] = pos + plan.steps_per_seq[i] - 1
            context_lens[i] = seq.num_tokens
            blocks = seq.blocks.blocks
            block_tables[i, : len(blocks)] = blocks
            slots[i] = seq.slot
            seeds[i] = seq.fallback_seed

        params_list = [s.params for s in seqs] + [None] * (b - len(seqs))
        gen_lens = [s.num_output_tokens for s in seqs] + [0] * (b - len(seqs))
        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )

        # FSM-constrained rows: per-row token masks (constrained rows run
        # exactly one step per dispatch, scheduler._allowed_steps); the
        # mask arg stays None on unconstrained batches so the common path
        # never pays the [B, V] transfer
        allowed_mask = None
        if any(seq.fsm is not None for seq in seqs):
            vocab = self.config.model_config.vocab_size
            allowed_mask = np.ones((b, vocab), bool)
            for i, seq in enumerate(seqs):
                if seq.fsm is not None:
                    row = seq.fsm.allowed_row(seq.fsm_state)
                    # model vocab may exceed the tokenizer's (padded
                    # embeddings): ids the tokenizer can't spell stay banned
                    allowed_mask[i, : len(row)] = row
                    allowed_mask[i, len(row):] = False

        lora_idx = None
        if self.lora_stacks is not None:
            lora_idx = np.zeros(b, np.int32)
            for i, seq in enumerate(seqs):
                lora_idx[i] = seq.lora_slot

        spec_ok = False
        draft_catchups: list = []
        if self.spec is not None:
            spec_ok = all(seq.spec_eligible for seq in seqs)
            if spec_ok:
                # rows that decoded in mixed batches have a stale draft
                # cache; snapshot the chunk inputs that re-run their
                # missing tokens through the draft (all but the last
                # token, which is the propose input)
                for i, seq in enumerate(seqs):
                    end = seq.num_tokens - 1
                    if seq.draft_pos >= end:
                        continue
                    gap = seq.all_token_ids[seq.draft_pos:end]
                    bucket = self._seen_pad_len(len(gap))
                    ids = np.zeros(bucket, np.int32)
                    ids[: len(gap)] = gap
                    pos = seq.draft_pos + np.arange(bucket, dtype=np.int32)
                    slots = np.full(bucket, -1, np.int32)
                    slots[: len(gap)] = seq.blocks.slots_for_range(
                        seq.draft_pos, end
                    )
                    draft_catchups.append(
                        dict(
                            t=len(gap),
                            token_ids=ids,
                            positions=pos,
                            slot_mapping=slots,
                            block_table=block_tables[i],
                            start_pos=seq.draft_pos,
                        )
                    )

        return PreparedDecode(
            spec_ok=spec_ok,
            want_topn=any(
                seq.params.logprobs not in (None, 0) for seq in seqs
            ),
            draft_catchups=draft_catchups,
            num_seqs=len(seqs),
            num_steps=plan.num_steps,
            steps_per_seq=list(plan.steps_per_seq),
            token_ids=token_ids,
            positions=positions,
            limits=limits,
            context_lens=context_lens,
            block_tables=block_tables,
            slots=slots,
            tensors=tensors,
            allowed_mask=allowed_mask,
            lora_idx=lora_idx,
        )

    def prepare_chained_decode(
        self, plan: "DecodePlan", prev_prep: "PreparedDecode"
    ) -> "PreparedDecode":
        """Host inputs for the SUCCESSOR wave of ``prev_prep``, planned
        while that wave still executes (scheduler.schedule_chained):
        every per-row position/length/PRNG projection assumes the row
        consumes its full previous step budget; the input tokens stay on
        device (dispatch_chained_decode reads them from the in-flight
        wave's outputs)."""
        seqs = plan.seqs
        b = plan.batch_bucket
        prev_k = prev_prep.steps_per_seq

        token_ids = np.zeros(b, np.int32)  # overridden on device
        positions = np.zeros(b, np.int32)
        limits = np.full(b, -1, np.int32)
        context_lens = np.ones(b, np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        slots = np.full(b, -1, np.int32)
        seeds = np.zeros(b, np.uint32)
        chain_idx = np.zeros(b, np.int32)
        gen_lens = []
        for i, seq in enumerate(seqs):
            pos = seq.num_tokens - 1 + prev_k[i]
            positions[i] = pos
            limits[i] = pos + plan.steps_per_seq[i] - 1
            context_lens[i] = seq.num_tokens + prev_k[i]
            blocks = seq.blocks.blocks
            block_tables[i, : len(blocks)] = blocks
            slots[i] = seq.slot
            seeds[i] = seq.fallback_seed
            chain_idx[i] = prev_k[i] - 1
            gen_lens.append(seq.num_output_tokens + prev_k[i])

        params_list = [s.params for s in seqs] + [None] * (b - len(seqs))
        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens + [0] * (b - len(seqs)),
            fallback_seeds=seeds,
        )
        lora_idx = None
        if self.lora_stacks is not None:
            lora_idx = np.zeros(b, np.int32)
            for i, seq in enumerate(seqs):
                lora_idx[i] = seq.lora_slot

        return PreparedDecode(
            num_seqs=len(seqs),
            num_steps=plan.num_steps,
            steps_per_seq=list(plan.steps_per_seq),
            token_ids=token_ids,
            positions=positions,
            limits=limits,
            context_lens=context_lens,
            block_tables=block_tables,
            slots=slots,
            tensors=tensors,
            allowed_mask=None,  # FSM rows never chain (scheduler bail)
            lora_idx=lora_idx,
            chain_idx=chain_idx,
            want_topn=any(
                seq.params.logprobs not in (None, 0) for seq in seqs
            ),
        )

    def dispatch_chained_decode(self, prep: "PreparedDecode", prev_handle):
        """Enqueue the successor wave behind the in-flight one, feeding
        input tokens from its device-resident outputs."""
        lora = self.lora_stacks if prep.lora_idx is not None else None
        ints, floats = self._pack_decode_inputs(prep)

        def call():  # noqa: ANN202
            return self._chained_decode_fn(
                self.params,
                self.caches,
                self.seen,
                prev_handle,
                self._put(prep.chain_idx),
                self._put(ints),
                self._put(floats),
                self._put(prep.block_tables),
                None,
                lora,
                self._put(prep.lora_idx)
                if prep.lora_idx is not None
                else None,
                prep.num_steps,
                prep.want_topn,
            )

        self.caches, self.seen, packed_out = self._decode_kernel_retry(call)
        return packed_out

    def _pack_decode_inputs(self, prep: "PreparedDecode"):
        """Two transfer-packed arrays (see _build_decode_fn docstring)."""
        t = prep.tensors
        ints = np.stack([
            prep.token_ids, prep.positions, prep.limits,
            prep.context_lens, prep.slots,
            np.asarray(t.top_k, np.int32),
            np.asarray(t.len_penalty_start, np.int32),
            np.asarray(t.min_tokens, np.int32),
            np.asarray(t.eos_token_id, np.int32),
            np.asarray(t.gen_len, np.int32),
            np.asarray(t.base_key, np.uint32).view(np.int32),
        ]).astype(np.int32)
        floats = np.stack([
            t.temperature, t.top_p, t.typical_p,
            t.repetition_penalty, t.len_penalty_decay,
        ]).astype(np.float32)
        return ints, floats

    def _decode_kernel_retry(self, dispatch):  # noqa: ANN001
        """Serving-path decode-kernel degradation (ADVICE r5): a Mosaic
        rejection of the opted-in folded kernel steps down
        folded → perhead → xla (ops/attention.degrade_decode_kernel) and
        retries the dispatch instead of killing the engine at boot
        precompile or on the first live decode.  The variant is read at
        trace time inside the jitted model, and a failed compile leaves
        no cache entry, so the retry re-traces and picks up the
        degraded variant."""
        from vllm_tgis_adapter_tpu.ops import attention as attn_ops

        # getattr: the degradation unit test drives this helper unbound
        if getattr(self, "_ragged_backend", False):
            # the ragged path has ONE kernel — no variant chain to step
            # down; a lowering failure is a real error, not a retry
            return dispatch()
        while True:
            tried = attn_ops.decode_kernel_variant()
            try:
                return dispatch()
            except Exception as e:  # noqa: BLE001 — inspected, re-raised
                if not attn_ops.is_kernel_lowering_error(e):
                    raise
                # compare-and-swap on the variant THIS attempt traced
                # with: a concurrent replica's identical failure burns
                # one level between them, not two
                nxt = attn_ops.degrade_decode_kernel(tried)
                if nxt is None:
                    raise
                logger.warning(
                    "decode kernel %r failed to lower (%s: %s); "
                    "degrading to %r and retrying the dispatch",
                    tried, type(e).__name__, e, nxt,
                )

    def dispatch_decode(self, prep: "PreparedDecode"):
        """Enqueue the fused K-step decode; no blocking transfers.

        The speculative path runs multiple host-synchronised phases
        (propose → verify → accept) and cannot enqueue-only: it returns
        ``SYNC_DISPATCH`` and executes inside ``wait_decode`` instead.
        """
        failpoints.fire("runner.dispatch_decode")
        if prep.spec_ok:
            return SYNC_DISPATCH
        lora = self.lora_stacks if prep.lora_idx is not None else None
        ints, floats = self._pack_decode_inputs(prep)

        def call():  # noqa: ANN202
            return self._decode_fn(
                self.params,
                self.caches,
                self.seen,
                self._put(ints),
                self._put(floats),
                self._put(prep.block_tables),
                self._put(prep.allowed_mask)
                if prep.allowed_mask is not None
                else None,
                lora,
                self._put(prep.lora_idx)
                if prep.lora_idx is not None
                else None,
                prep.num_steps,
                prep.want_topn,
            )

        self.caches, self.seen, packed_out = self._decode_kernel_retry(call)
        return packed_out

    def wait_decode(
        self, prep: "PreparedDecode", handle
    ) -> list[list[SampledToken]]:
        """Blocking half: per-seq token lists (row i gets UP TO
        ``steps_per_seq[i]`` entries; the engine stops consuming a row's
        list at EOS/stop-string)."""
        if handle is SYNC_DISPATCH:
            return self.spec.run(prep)
        # [K, B, 3+2W] — one fetch per wave
        host = _HostSamplerOutput.from_packed(handle)
        return [
            [host.token(k, i) for k in range(prep.steps_per_seq[i])]
            for i in range(prep.num_seqs)
        ]

    def execute_decode(self, prep: "PreparedDecode") -> list[list[SampledToken]]:
        """Device half; see wait_decode for the result contract."""
        return self.wait_decode(prep, self.dispatch_decode(prep))

    def run_decode(self, plan: "DecodePlan") -> list[list[SampledToken]]:
        return self.execute_decode(self.prepare_decode(plan))
