"""Model runner: scheduler plans → jitted device programs → sampled tokens.

Owns the jit-compiled prefill/decode functions, the device-resident KV
caches, the seen-token matrix for repetition penalties, and the sampler
invocation.  All shapes flowing into jit are drawn from the scheduler's
buckets, so the compile count is bounded by
``len(prefill_buckets) + len(batch_buckets)`` (SURVEY.md §7 "XLA
recompilation discipline").
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod
from vllm_tgis_adapter_tpu.engine.sampler import TOPN_WIDTH, SamplingTensors
from vllm_tgis_adapter_tpu.logging import init_logger

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import EngineConfig
    from vllm_tgis_adapter_tpu.engine.scheduler import DecodePlan, PrefillPlan

logger = init_logger(__name__)


@dataclasses.dataclass
class SampledToken:
    """Host-side result for one sequence after one step."""

    token_id: int
    logprob: float
    rank: int
    topn_ids: list[int]
    topn_logprobs: list[float]


@dataclasses.dataclass
class PromptLogprobInfo:
    """Per-position prompt logprob table (position 0 has no entry)."""

    logprobs: list[float]  # [T-1] for positions 1..T-1
    ranks: list[int]
    topn_ids: list[list[int]]
    topn_logprobs: list[list[float]]


class ModelRunner:
    def __init__(self, config: "EngineConfig", model, params, mesh=None):
        self.config = config
        self.model = model
        cache_cfg = config.cache_config
        mcfg = config.model_config
        self.block_size = cache_cfg.block_size
        self.num_slots = cache_cfg.num_blocks * cache_cfg.block_size
        self.max_blocks_per_seq = -(-mcfg.max_model_len // self.block_size)

        # distributed: shard params/caches over the mesh; the XLA SPMD
        # partitioner propagates Megatron TP through the step fns
        # (parallel/sharding.py).  tp=1 single-chip keeps the fast path.
        pcfg = config.parallel_config
        if mesh is None:
            from vllm_tgis_adapter_tpu.parallel.mesh import (
                mesh_from_parallel_config,
            )

            mesh = mesh_from_parallel_config(pcfg)
        self.mesh = mesh
        if mesh is not None:
            from vllm_tgis_adapter_tpu.parallel import (
                cache_sharding,
                data_sharding,
                shard_llama_params,
                validate_tp_divisibility,
            )

            validate_tp_divisibility(mcfg, mesh.shape["tp"])
            params = shard_llama_params(mesh, params)
            # allocate the cache sharded from the start: the pool is sized
            # against the mesh's AGGREGATE HBM, so materialising it on one
            # device first would OOM exactly like an unsharded weight load
            sh = cache_sharding(mesh)
            caches = jax.jit(
                lambda: model.make_kv_caches(
                    self.num_slots, cache_cfg.cache_dtype
                ),
                out_shardings=(sh, sh),
            )()
            self._data_sharding = data_sharding(mesh)
        else:
            caches = model.make_kv_caches(self.num_slots, cache_cfg.cache_dtype)
            self._data_sharding = None
        self.params = params
        self.caches = caches

        # buffer donation lets XLA update the KV cache in place; host
        # platforms don't implement donation and warn, so gate it
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._prefill_fn = jax.jit(model.prefill, donate_argnums=donate)
        self._decode_fn = jax.jit(
            model.decode, static_argnums=(7,), donate_argnums=donate
        )

        max_seqs = config.scheduler_config.max_num_seqs
        self.seen = self._put(jnp.zeros((max_seqs, mcfg.vocab_size), bool))
        self._rng = np.random.default_rng(config.seed)

    def _put(self, x) -> jax.Array:
        """Host array → device; replicated over the mesh when distributed
        so every tp shard sees the full batch (parallel/sharding.py)."""
        if self._data_sharding is not None:
            return jax.device_put(x, self._data_sharding)
        return jnp.asarray(x)

    def new_fallback_seed(self) -> int:
        """Engine-drawn PRNG material for requests without an explicit seed."""
        return int(self._rng.integers(0, 2**32, dtype=np.uint32))

    # --------------------------------------------------------------- prefill

    def run_prefill(
        self, plan: "PrefillPlan"
    ) -> tuple[SampledToken, Optional[PromptLogprobInfo]]:
        seq = plan.seq
        t = len(plan.token_ids)
        bucket = plan.bucket_len

        token_ids = np.zeros(bucket, np.int32)
        token_ids[:t] = plan.token_ids
        positions = np.arange(bucket, dtype=np.int32)
        slot_mapping = np.full(bucket, -1, np.int32)
        slot_mapping[:t] = plan.slots

        want_prompt_lp = seq.params.prompt_logprobs is not None
        logits_indices = (
            np.arange(bucket, dtype=np.int32)
            if want_prompt_lp
            else np.asarray([t - 1], np.int32)
        )

        logits, self.caches = self._prefill_fn(
            self.params,
            self.caches,
            self._put(token_ids),
            self._put(positions),
            self._put(slot_mapping),
            self._put(np.asarray(t, np.int32)),
            self._put(logits_indices),
        )

        prompt_info = None
        if want_prompt_lp:
            lp, rank, tn_ids, tn_lp = sampler_mod.prompt_logprob_info(
                logits, jnp.asarray(token_ids)
            )
            n = t - 1  # rows 0..t-2 describe positions 1..t-1
            prompt_info = PromptLogprobInfo(
                logprobs=np.asarray(lp)[:n].tolist(),
                ranks=np.asarray(rank)[:n].tolist(),
                topn_ids=np.asarray(tn_ids)[:n].tolist(),
                topn_logprobs=np.asarray(tn_lp)[:n].tolist(),
            )
            last_logits = logits[t - 1][None]
        else:
            last_logits = logits

        # seed this row's seen-token matrix with the prompt, then sample
        row_tokens = np.full(bucket, -1, np.int32)
        row_tokens[:t] = plan.token_ids
        self.seen = sampler_mod.set_seen_row(
            self.seen, self._put(np.asarray(seq.slot)), self._put(row_tokens)
        )
        result = self._sample(last_logits, [seq])
        return result[0], prompt_info

    # ---------------------------------------------------------------- decode

    def run_decode(self, plan: "DecodePlan") -> list[SampledToken]:
        seqs = plan.seqs
        n, b = len(seqs), plan.batch_bucket

        token_ids = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        slot_mapping = np.full(b, -1, np.int32)
        context_lens = np.ones(b, np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        for i, seq in enumerate(seqs):
            pos = seq.num_tokens - 1  # the last sampled token runs this step
            token_ids[i] = seq.all_token_ids[-1]
            positions[i] = pos
            slot_mapping[i] = seq.blocks.slot_for(pos)
            context_lens[i] = seq.num_tokens
            blocks = seq.blocks.blocks
            block_tables[i, : len(blocks)] = blocks

        logits, self.caches = self._decode_fn(
            self.params,
            self.caches,
            self._put(token_ids),
            self._put(positions),
            self._put(slot_mapping),
            self._put(block_tables),
            self._put(context_lens),
            self.block_size,
        )
        return self._sample(logits, seqs)

    # --------------------------------------------------------------- sampler

    def _sample(self, logits: jax.Array, seqs) -> list[SampledToken]:
        """Sample one token per row; rows beyond ``len(seqs)`` are padding."""
        b = logits.shape[0]
        params_list = [s.params for s in seqs] + [None] * (b - len(seqs))
        gen_lens = [s.num_output_tokens for s in seqs] + [0] * (b - len(seqs))
        seeds = np.zeros(b, np.uint32)
        slots = np.full(b, -1, np.int32)
        for i, s in enumerate(seqs):
            seeds[i] = s.fallback_seed
            slots[i] = s.slot

        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )
        seen_rows = jnp.take(
            self.seen, jnp.clip(jnp.asarray(slots), 0, None), axis=0
        )
        out = sampler_mod.sample(logits, seen_rows, tensors)
        self.seen = sampler_mod.update_seen(
            self.seen, jnp.asarray(slots), out.tokens
        )

        tokens = np.asarray(out.tokens)
        logprobs = np.asarray(out.logprob)
        ranks = np.asarray(out.rank)
        tn_ids = np.asarray(out.topn_ids)
        tn_lp = np.asarray(out.topn_logprobs)
        return [
            SampledToken(
                token_id=int(tokens[i]),
                logprob=float(logprobs[i]),
                rank=int(ranks[i]),
                topn_ids=tn_ids[i].tolist(),
                topn_logprobs=tn_lp[i].tolist(),
            )
            for i in range(len(seqs))
        ]
