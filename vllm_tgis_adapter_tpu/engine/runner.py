"""Model runner: scheduler plans → jitted device programs → sampled tokens.

Owns the jit-compiled prefill/decode functions, the device-resident KV
caches, the seen-token matrix for repetition penalties, and the sampler
invocation.  All shapes flowing into jit are drawn from the scheduler's
buckets, so the compile count is bounded by
``len(prefill_buckets) + len(batch_buckets)`` (SURVEY.md §7 "XLA
recompilation discipline").
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_tgis_adapter_tpu.engine import sampler as sampler_mod
from vllm_tgis_adapter_tpu.engine.sampler import TOPN_WIDTH, SamplingTensors
from vllm_tgis_adapter_tpu.logging import init_logger

if TYPE_CHECKING:
    from vllm_tgis_adapter_tpu.engine.config import EngineConfig
    from vllm_tgis_adapter_tpu.engine.scheduler import DecodePlan, PrefillPlan

logger = init_logger(__name__)


@dataclasses.dataclass
class SampledToken:
    """Host-side result for one sequence after one step."""

    token_id: int
    logprob: float
    rank: int
    topn_ids: list[int]
    topn_logprobs: list[float]


@dataclasses.dataclass
class PromptLogprobInfo:
    """Per-position prompt logprob table (position 0 has no entry)."""

    logprobs: list[float]  # [T-1] for positions 1..T-1
    ranks: list[int]
    topn_ids: list[list[int]]
    topn_logprobs: list[list[float]]


@dataclasses.dataclass
class _HostSamplerOutput:
    """Sampler results pulled to host as [K, B] numpy arrays."""

    tokens: "np.ndarray"
    logprobs: "np.ndarray"
    ranks: "np.ndarray"
    topn_ids: "np.ndarray"  # [K, B, W]
    topn_logprobs: "np.ndarray"

    @staticmethod
    def from_device(outs) -> "_HostSamplerOutput":
        return _HostSamplerOutput(
            tokens=np.asarray(outs.tokens),
            logprobs=np.asarray(outs.logprob),
            ranks=np.asarray(outs.rank),
            topn_ids=np.asarray(outs.topn_ids),
            topn_logprobs=np.asarray(outs.topn_logprobs),
        )

    def token(self, k: int, i: int) -> "SampledToken":
        return SampledToken(
            token_id=int(self.tokens[k, i]),
            logprob=float(self.logprobs[k, i]),
            rank=int(self.ranks[k, i]),
            topn_ids=self.topn_ids[k, i].tolist(),
            topn_logprobs=self.topn_logprobs[k, i].tolist(),
        )


class ModelRunner:
    def __init__(self, config: "EngineConfig", model, params, mesh=None):
        self.config = config
        self.model = model
        cache_cfg = config.cache_config
        mcfg = config.model_config
        self.block_size = cache_cfg.block_size
        self.num_slots = cache_cfg.num_blocks * cache_cfg.block_size
        self.max_blocks_per_seq = -(-mcfg.max_model_len // self.block_size)

        # distributed: shard params/caches over the mesh; the XLA SPMD
        # partitioner propagates Megatron TP through the step fns
        # (parallel/sharding.py).  tp=1 single-chip keeps the fast path.
        pcfg = config.parallel_config
        if mesh is None:
            from vllm_tgis_adapter_tpu.parallel.mesh import (
                mesh_from_parallel_config,
            )

            mesh = mesh_from_parallel_config(pcfg)
        self.mesh = mesh
        if mesh is not None:
            from vllm_tgis_adapter_tpu.parallel import (
                cache_sharding,
                data_sharding,
                shard_llama_params,
                validate_tp_divisibility,
            )

            validate_tp_divisibility(mcfg, mesh.shape["tp"])
            params = shard_llama_params(mesh, params)
            # allocate the cache sharded from the start: the pool is sized
            # against the mesh's AGGREGATE HBM, so materialising it on one
            # device first would OOM exactly like an unsharded weight load
            sh = cache_sharding(mesh)
            caches = jax.jit(
                lambda: model.make_kv_caches(
                    self.num_slots, cache_cfg.cache_dtype
                ),
                out_shardings=(sh, sh),
            )()
            self._data_sharding = data_sharding(mesh)
        else:
            caches = model.make_kv_caches(self.num_slots, cache_cfg.cache_dtype)
            self._data_sharding = None
        self.params = params
        self.caches = caches
        # pallas kernels must be shard_map-wrapped under a TP mesh; the
        # mesh travels on the model so each engine's retraces see its own
        # (ops/attention.py dispatch)
        model.mesh = mesh

        # buffer donation lets XLA update the KV cache in place; host
        # platforms don't implement donation and warn, so gate it
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._prefill_fn = jax.jit(model.prefill, donate_argnums=donate)
        self._decode_fn = self._build_decode_fn()

        max_seqs = config.scheduler_config.max_num_seqs
        self.seen = self._put(jnp.zeros((max_seqs, mcfg.vocab_size), bool))
        self._rng = np.random.default_rng(config.seed)
        self.lora_stacks = None
        self._lora_version = 0  # manager starts at 0 = nothing loaded

    def sync_lora(self, manager) -> None:
        """Rebuild the stacked adapter tensors when the registry changed
        (hot load/evict).  One compiled program serves every adapter —
        slots and padded ranks keep shapes constant across reloads."""
        if manager is None or manager.version == self._lora_version:
            return
        from vllm_tgis_adapter_tpu.engine.lora import build_lora_stacks

        lcfg = self.config.lora_config
        stacks = build_lora_stacks(
            self.config.model_config, manager.max_loras,
            lcfg.max_lora_rank, manager,
        )
        self.lora_stacks = jax.tree.map(self._put, stacks)
        self._lora_version = manager.version

    def _build_decode_fn(self):
        """Fused K-step decode+sample program (SURVEY.md §7 recompilation
        discipline: one compiled program per batch-width bucket).

        A ``lax.scan`` over the step axis runs the whole
        decode → penalties → sample → feed-back loop on device, so the
        host pays one dispatch and one [K, B] result transfer for K
        tokens per sequence instead of K round-trips.  Per-step KV slots
        are computed on device from the block tables; rows finish early
        via the ``limits`` mask (their writes are dropped and their
        sampled tokens discarded by the host).
        """
        model = self.model
        block_size = self.block_size

        def decode_steps(
            params,
            caches,
            seen,  # [max_seqs, V] full seen-token matrix (carried)
            tokens,  # [B] last sampled token per row
            positions0,  # [B] position of that token
            limits,  # [B] last position each row may run (mask after)
            block_tables,  # [B, max_blocks]
            context_lens0,  # [B] length including the current token
            row_slots,  # [B] row index into ``seen``; -1 pads
            tensors: SamplingTensors,
            allowed_mask,  # [B, V] bool or None (FSM-constrained rows)
            lora,  # LoRAStacks or None
            lora_idx,  # [B] adapter slot per row or None
            num_steps: int,  # static: steps fused into this dispatch
        ):
            b = tokens.shape[0]
            rows = jnp.clip(row_slots, 0, None)
            max_blocks = block_tables.shape[1]

            def step(carry, k):
                caches, seen, tokens = carry
                pos = positions0 + k
                active = (pos <= limits) & (row_slots >= 0)
                blk = jnp.take_along_axis(
                    block_tables,
                    jnp.clip(pos // block_size, 0, max_blocks - 1)[:, None],
                    axis=1,
                )[:, 0]
                slot = jnp.where(
                    active, blk * block_size + pos % block_size, -1
                )
                logits, caches = model.decode(
                    params, caches, tokens, pos, slot, block_tables,
                    context_lens0 + k, block_size, lora, lora_idx,
                )
                t_k = dataclasses.replace(
                    tensors, gen_len=tensors.gen_len + k
                )
                seen_rows = jnp.take(seen, rows, axis=0)
                out = sampler_mod.sample(
                    logits, seen_rows, t_k, allowed_mask=allowed_mask
                )
                seen = sampler_mod.update_seen(
                    seen, jnp.where(active, row_slots, -1), out.tokens
                )
                return (caches, seen, out.tokens), out

            (caches, seen, _), outs = jax.lax.scan(
                step, (caches, seen, tokens), jnp.arange(num_steps)
            )
            return caches, seen, outs

        donate = (1, 2) if jax.default_backend() == "tpu" else ()
        return jax.jit(decode_steps, static_argnums=(13,),
                       donate_argnums=donate)

    def _put(self, x) -> jax.Array:
        """Host array → device; replicated over the mesh when distributed
        so every tp shard sees the full batch (parallel/sharding.py)."""
        if self._data_sharding is not None:
            return jax.device_put(x, self._data_sharding)
        return jnp.asarray(x)

    def new_fallback_seed(self) -> int:
        """Engine-drawn PRNG material for requests without an explicit seed."""
        return int(self._rng.integers(0, 2**32, dtype=np.uint32))

    # --------------------------------------------------------------- prefill

    def run_prefill(
        self, plan: "PrefillPlan"
    ) -> tuple[SampledToken, Optional[PromptLogprobInfo]]:
        seq = plan.seq
        t = len(plan.token_ids)
        bucket = plan.bucket_len

        token_ids = np.zeros(bucket, np.int32)
        token_ids[:t] = plan.token_ids
        positions = np.arange(bucket, dtype=np.int32)
        slot_mapping = np.full(bucket, -1, np.int32)
        slot_mapping[:t] = plan.slots

        want_prompt_lp = seq.params.prompt_logprobs is not None
        logits_indices = (
            np.arange(bucket, dtype=np.int32)
            if want_prompt_lp
            else np.asarray([t - 1], np.int32)
        )

        lora_args = ()
        if self.lora_stacks is not None:
            lora_args = (
                self.lora_stacks,
                self._put(np.asarray(seq.lora_slot, np.int32)),
            )
        logits, self.caches = self._prefill_fn(
            self.params,
            self.caches,
            self._put(token_ids),
            self._put(positions),
            self._put(slot_mapping),
            self._put(np.asarray(t, np.int32)),
            self._put(logits_indices),
            *lora_args,
        )

        prompt_info = None
        if want_prompt_lp:
            lp, rank, tn_ids, tn_lp = sampler_mod.prompt_logprob_info(
                logits, jnp.asarray(token_ids)
            )
            n = t - 1  # rows 0..t-2 describe positions 1..t-1
            prompt_info = PromptLogprobInfo(
                logprobs=np.asarray(lp)[:n].tolist(),
                ranks=np.asarray(rank)[:n].tolist(),
                topn_ids=np.asarray(tn_ids)[:n].tolist(),
                topn_logprobs=np.asarray(tn_lp)[:n].tolist(),
            )
            last_logits = logits[t - 1][None]
        else:
            last_logits = logits

        # seed this row's seen-token matrix with the prompt, then sample
        row_tokens = np.full(bucket, -1, np.int32)
        row_tokens[:t] = plan.token_ids
        self.seen = sampler_mod.set_seen_row(
            self.seen, self._put(np.asarray(seq.slot)), self._put(row_tokens)
        )
        allowed_mask = None
        if seq.fsm is not None:
            vocab = self.config.model_config.vocab_size
            row = np.zeros(vocab, bool)
            fsm_row = seq.fsm.allowed_row(seq.fsm_state)
            row[: len(fsm_row)] = fsm_row
            allowed_mask = self._put(row[None, :])
        result = self._sample(last_logits, [seq], allowed_mask=allowed_mask)
        return result[0], prompt_info

    # ---------------------------------------------------------------- decode

    def run_decode(self, plan: "DecodePlan") -> list[list[SampledToken]]:
        """One fused K-step dispatch; returns per-seq token lists.

        Row i's list has ``plan.steps_per_seq[i]`` entries; the host-side
        engine stops consuming a row's list at EOS/stop-string.
        """
        seqs = plan.seqs
        b = plan.batch_bucket

        token_ids = np.zeros(b, np.int32)
        positions = np.zeros(b, np.int32)
        limits = np.full(b, -1, np.int32)
        context_lens = np.ones(b, np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        slots = np.full(b, -1, np.int32)
        seeds = np.zeros(b, np.uint32)
        for i, seq in enumerate(seqs):
            pos = seq.num_tokens - 1  # the last sampled token runs first
            token_ids[i] = seq.all_token_ids[-1]
            positions[i] = pos
            limits[i] = pos + plan.steps_per_seq[i] - 1
            context_lens[i] = seq.num_tokens
            blocks = seq.blocks.blocks
            block_tables[i, : len(blocks)] = blocks
            slots[i] = seq.slot
            seeds[i] = seq.fallback_seed

        params_list = [s.params for s in seqs] + [None] * (b - len(seqs))
        gen_lens = [s.num_output_tokens for s in seqs] + [0] * (b - len(seqs))
        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )

        # FSM-constrained rows: per-row token masks (constrained rows run
        # exactly one step per dispatch, scheduler._allowed_steps); the
        # mask arg stays None on unconstrained batches so the common path
        # never pays the [B, V] transfer
        allowed_mask = None
        if any(seq.fsm is not None for seq in seqs):
            vocab = self.config.model_config.vocab_size
            mask = np.ones((b, vocab), bool)
            for i, seq in enumerate(seqs):
                if seq.fsm is not None:
                    row = seq.fsm.allowed_row(seq.fsm_state)
                    # model vocab may exceed the tokenizer's (padded
                    # embeddings): ids the tokenizer can't spell stay banned
                    mask[i, : len(row)] = row
                    mask[i, len(row):] = False
            allowed_mask = self._put(mask)

        lora, lora_idx = None, None
        if self.lora_stacks is not None:
            lora = self.lora_stacks
            idx = np.zeros(b, np.int32)
            for i, seq in enumerate(seqs):
                idx[i] = seq.lora_slot
            lora_idx = self._put(idx)

        self.caches, self.seen, outs = self._decode_fn(
            self.params,
            self.caches,
            self.seen,
            self._put(token_ids),
            self._put(positions),
            self._put(limits),
            self._put(block_tables),
            self._put(context_lens),
            self._put(slots),
            jax.tree.map(self._put, tensors),
            allowed_mask,
            lora,
            lora_idx,
            plan.num_steps,
        )

        host = _HostSamplerOutput.from_device(outs)  # [K, B] arrays
        return [
            [host.token(k, i) for k in range(plan.steps_per_seq[i])]
            for i in range(len(seqs))
        ]

    # --------------------------------------------------------------- sampler

    def _sample(
        self, logits: jax.Array, seqs, allowed_mask=None
    ) -> list[SampledToken]:
        """Sample one token per row; rows beyond ``len(seqs)`` are padding."""
        b = logits.shape[0]
        params_list = [s.params for s in seqs] + [None] * (b - len(seqs))
        gen_lens = [s.num_output_tokens for s in seqs] + [0] * (b - len(seqs))
        seeds = np.zeros(b, np.uint32)
        slots = np.full(b, -1, np.int32)
        for i, s in enumerate(seqs):
            seeds[i] = s.fallback_seed
            slots[i] = s.slot

        tensors = SamplingTensors.from_params(
            params_list,
            eos_token_id=self.config.model_config.eos_token_id,
            gen_lens=gen_lens,
            fallback_seeds=seeds,
        )
        seen_rows = jnp.take(
            self.seen, jnp.clip(jnp.asarray(slots), 0, None), axis=0
        )
        out = sampler_mod.sample(
            logits, seen_rows, tensors, allowed_mask=allowed_mask
        )
        self.seen = sampler_mod.update_seen(
            self.seen, jnp.asarray(slots), out.tokens
        )

        host = _HostSamplerOutput.from_device(
            jax.tree.map(lambda x: x[None], out)  # add a unit step axis
        )
        return [host.token(0, i) for i in range(len(seqs))]
